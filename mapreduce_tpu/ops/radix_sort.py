"""Pallas LSD radix sort over the hash-key lanes + fused partition plan.

The reference's whole shuffle is a sort (per-mapper sorted k/v files plus
a k-way heap merge, fs.lua/heap.lua); the device twin inherited that as a
``lax.sort`` comparator — the ~100s cold-compile monster that forced the
argsort tier.  Keys here are already uint32 hashes, so radix — not
comparison — is the natural formulation.  This module provides:

``radix_sort_pairs(k1, k2)``
    Stable least-significant-digit radix sort of the 64-bit key formed by
    ``(k1 hi, k2 lo)``, returning ``(k1s, k2s, perm)`` bit-identical to
    ``jax.lax.sort((k1, k2, iota), num_keys=2)``: 4-bit digits, 8 passes
    per 32-bit lane (16 total), each pass a tile-local digit histogram
    kernel (``radix_hist``) → exclusive prefix-sums across tiles via the
    segscan ladder → a stable in-kernel scatter by rank
    (``radix_scatter``).  Stability is structural: within a tile the rank
    is an input-order cumulative count, across tiles the prefix offsets
    preserve tile order, so equal keys keep input order in every pass and
    LSD induction pins the whole sort — no comparator, no iota tie-break
    lane in the sort itself (``perm`` rides along as a payload lane).

``radix_partition_plan(dest, num_partitions)``
    The fused-exchange half: one histogram pass over the destination
    digit yields BOTH the per-destination row counts (the exchange
    traffic-matrix row, bit-equal to the classic
    ``onehot.sum(axis=0)`` count pass it deletes) and the stable
    per-destination scatter ranks that place each record in its
    destination bucket (``radix_rank`` kernel).

Unsigned bit order == unsigned numeric order, so the full uint32 range
(including sign-bit edge values 0x7FFFFFFF/0x80000000 and the 0xFFFFFFFF
sentinel) sorts correctly with no bias step.

Off-TPU the kernels run under the Pallas interpreter via
``pallas_compat`` (the in-kernel scatter is jnp ``.at[].set`` — exact in
interpret mode; on TPU it lowers through Mosaic's scatter path, the one
stage of this module that is TPU-generation sensitive).  Like every
kernel module this file is under the monotonic-only AST lint: it must
read no clocks at all.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from . import pallas_compat
from .segscan import ladder_cumsum

#: Digit width of one LSD pass.  4 bits / 16 buckets keeps the per-pass
#: onehot-rank work at 16 lanes per element (~256 ops over all 16 passes,
#: comparable to the comparator formulation's n·log n) while the pass
#: count stays low enough that 2 lanes × 8 passes cover uint32.
RADIX_BITS = 4
RADIX = 1 << RADIX_BITS
_DIGIT_MASK = np.uint32(RADIX - 1)
#: Passes over the 64-bit (k1 hi, k2 lo) key.
RADIX_PASSES = 2 * (32 // RADIX_BITS)
#: Default elements per tile (one grid step); multiple of the 128-lane
#: TPU vector width.
RADIX_BLOCK = 4096
_LANES = 128
_SENT = np.uint32(0xFFFFFFFF)


def _blocking(n: int, block: Optional[int]) -> Tuple[int, int, int]:
    """Round ``n`` up to tiles: returns (npad, tiles, block)."""
    b = RADIX_BLOCK if block is None else int(block)
    b = max(_LANES, (b // _LANES) * _LANES)
    npad = -(-max(int(n), 1) // b) * b
    return npad, npad // b, b


def _tile_offsets(hist: jax.Array) -> jax.Array:
    """Exclusive prefix over the tile axis, per digit: [T, R] -> [T, R].

    Reuses the segscan ladder (inclusive cumsum along the last axis) by
    transposing the tile axis into lane position.
    """
    return ladder_cumsum(hist.T).T - hist


def _digit_base(hist: jax.Array) -> jax.Array:
    """Exclusive prefix of digit totals: [T, R] -> [R]."""
    tot = jnp.sum(hist, axis=0)
    return ladder_cumsum(tot) - tot


# -- kernels -----------------------------------------------------------------


def _hist_kernel(d_ref, h_ref, *, nbuckets):
    """Per-tile digit histogram: d [1, B] int32 -> h [1, R] int32."""
    d = d_ref[0, :]
    iota = jax.lax.broadcasted_iota(jnp.int32, (d.shape[0], nbuckets), 1)
    onehot = (d[:, None] == iota).astype(jnp.int32)
    h_ref[0, :] = jnp.sum(onehot, axis=0)


def _stable_rank(d, nbuckets):
    """Input-order rank of each element among equal digits in its tile."""
    iota = jax.lax.broadcasted_iota(jnp.int32, (d.shape[0], nbuckets), 1)
    onehot = (d[:, None] == iota).astype(jnp.int32)
    csum = jnp.cumsum(onehot, axis=0)
    return jnp.take_along_axis(csum - 1, d[:, None], axis=1)[:, 0]


def _rank_kernel(d_ref, off_ref, r_ref, *, nbuckets):
    """Global stable rank within each digit bucket (fused-exchange path):
    d [1, B], off [1, R] (exclusive tile offsets) -> r [1, B]."""
    d = d_ref[0, :]
    r_ref[0, :] = off_ref[0, :][d] + _stable_rank(d, nbuckets)


def _scatter_kernel(d_ref, off_ref, a1_ref, a2_ref, p_ref,
                    o1_ref, o2_ref, op_ref, *, nbuckets):
    """Stable scatter of one tile's lanes to global sorted positions.

    Outputs are full-array blocks revisited by every grid step; each
    global position is written exactly once across the grid because the
    per-pass destination map is a permutation.
    """
    from jax.experimental import pallas as pl

    b = pl.program_id(0)

    @pl.when(b == 0)
    def _init():
        for ref in (o1_ref, o2_ref, op_ref):
            ref[...] = jnp.zeros(ref.shape, ref.dtype)

    d = d_ref[0, :]
    pos = off_ref[0, :][d] + _stable_rank(d, nbuckets)
    for src, dst in ((a1_ref, o1_ref), (a2_ref, o2_ref), (p_ref, op_ref)):
        cur = dst[...]
        dst[...] = cur.at[0, pos].set(src[0, :])


# -- kernel callers ----------------------------------------------------------


def _tile_hist(d2, nbuckets, interpret):
    """d2 [T, B] int32 -> per-tile digit histogram [T, R] int32."""
    from jax.experimental import pallas as pl

    tiles, block = d2.shape
    return pallas_compat.pallas_call(
        functools.partial(_hist_kernel, nbuckets=nbuckets),
        name="radix_hist",
        interpret=interpret,
        grid=(tiles,),
        in_specs=[pl.BlockSpec((1, block), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((1, nbuckets), lambda i: (i, 0)),
        out_shape=pallas_compat.sds((tiles, nbuckets), jnp.int32, d2),
    )(d2)


def _tile_rank(d2, off, nbuckets, interpret):
    """Global stable ranks: d2 [T, B], off [T, R] -> [T, B] int32."""
    from jax.experimental import pallas as pl

    tiles, block = d2.shape
    return pallas_compat.pallas_call(
        functools.partial(_rank_kernel, nbuckets=nbuckets),
        name="radix_rank",
        interpret=interpret,
        grid=(tiles,),
        in_specs=[pl.BlockSpec((1, block), lambda i: (i, 0)),
                  pl.BlockSpec((1, nbuckets), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((1, block), lambda i: (i, 0)),
        out_shape=pallas_compat.sds((tiles, block), jnp.int32, d2),
    )(d2, off)


def _tile_scatter(d2, off, a1, a2, pr, interpret):
    """One stable scatter pass: tile lanes -> globally sorted lanes."""
    from jax.experimental import pallas as pl

    tiles, block = d2.shape
    npad = tiles * block
    tile = pl.BlockSpec((1, block), lambda i: (i, 0))
    full = pl.BlockSpec((1, npad), lambda i: (0, 0))
    o1, o2, op_ = pallas_compat.pallas_call(
        functools.partial(_scatter_kernel, nbuckets=RADIX),
        name="radix_scatter",
        interpret=interpret,
        grid=(tiles,),
        in_specs=[tile, pl.BlockSpec((1, RADIX), lambda i: (i, 0)),
                  tile, tile, tile],
        out_specs=[full, full, full],
        out_shape=[pallas_compat.sds((1, npad), jnp.uint32, a1),
                   pallas_compat.sds((1, npad), jnp.uint32, a2),
                   pallas_compat.sds((1, npad), jnp.int32, pr)],
    )(d2, off, a1, a2, pr)
    return o1[0], o2[0], op_[0]


# -- public API --------------------------------------------------------------


def _radix_pass(digits, a1, a2, pr, tiles, block, interpret):
    d2 = digits.reshape(tiles, block)
    hist = _tile_hist(d2, RADIX, interpret)
    off = _digit_base(hist)[None, :] + _tile_offsets(hist)
    return _tile_scatter(d2, off, a1.reshape(tiles, block),
                         a2.reshape(tiles, block),
                         pr.reshape(tiles, block), interpret)


def radix_sort_pairs(k1: jax.Array, k2: jax.Array, *,
                     block: Optional[int] = None,
                     interpret: Optional[bool] = None,
                     ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Stable radix sort by the 64-bit key ``(k1 hi, k2 lo)``.

    Returns ``(k1s, k2s, perm)`` bit-identical to
    ``jax.lax.sort((k1, k2, arange(n, int32)), num_keys=2)``; gather any
    further record lanes by ``perm``.  ``k1``/``k2`` must be uint32.
    """
    n = int(k1.shape[0])
    if n == 0:
        return k1, k2, jnp.zeros((0,), jnp.int32)
    npad, tiles, blk = _blocking(n, block)
    pad = npad - n
    # Pad rows carry the maximal key and come after every real row, so
    # stability keeps them in the tail slots [n:npad] and the truncation
    # below is exact.
    a1 = jnp.pad(k1, (0, pad), constant_values=_SENT)
    a2 = jnp.pad(k2, (0, pad), constant_values=_SENT)
    pr = jnp.arange(npad, dtype=jnp.int32)

    # One lax.scan per key lane over the 8 digit shifts: the pass body
    # (two kernel programs) is traced ONCE per lane instead of 8 times,
    # an ~8x cut in trace/compile work with bit-identical semantics —
    # the shift rides as a traced scalar through the digit extraction.
    def _lane_pass(lane):
        def body(carry, shift):
            a1, a2, pr = carry
            src = a2 if lane == 1 else a1
            digits = ((src >> shift) & _DIGIT_MASK).astype(jnp.int32)
            return _radix_pass(digits, a1, a2, pr, tiles, blk,
                               interpret), None
        return body

    shifts = jnp.arange(0, 32, RADIX_BITS, dtype=jnp.uint32)
    for lane in (1, 0):  # low lane first: LSD over the 64-bit key
        (a1, a2, pr), _ = jax.lax.scan(_lane_pass(lane), (a1, a2, pr),
                                       shifts)
    return a1[:n], a2[:n], pr[:n]


def radix_partition_plan(dest: jax.Array, num_partitions: int, *,
                         block: Optional[int] = None,
                         interpret: Optional[bool] = None,
                         ) -> Tuple[jax.Array, jax.Array]:
    """Fused-exchange plan from one destination-digit histogram pass.

    ``dest`` is int32 in ``[0, P]`` where ``P == num_partitions`` marks a
    dropped (invalid) row — the encoding ``partition_exchange`` already
    produces.  Returns ``(rank, counts)``:

    - ``rank`` [n] int32: stable input-order index of each row within
      its destination bucket (rows marked ``P`` rank among themselves
      and are dropped by the out-of-bounds scatter downstream);
    - ``counts`` [P] int32: valid rows per destination **before**
      capacity capping — the exchange traffic-matrix row, bit-equal to
      the classic ``onehot.sum(axis=0)`` recompute this plan deletes.

    One histogram kernel feeds both: the per-tile exclusive prefix is
    the scatter offset ladder, the digit totals are the matrix row.
    """
    p = int(num_partitions)
    nbuckets = p + 1  # one overflow bucket for dropped rows
    n = int(dest.shape[0])
    if n == 0:
        return (jnp.zeros((0,), jnp.int32),
                jnp.zeros((p,), jnp.int32))
    npad, tiles, blk = _blocking(n, block)
    d = jnp.pad(dest.astype(jnp.int32), (0, npad - n), constant_values=p)
    d2 = d.reshape(tiles, blk)
    hist = _tile_hist(d2, nbuckets, interpret)
    rank = _tile_rank(d2, _tile_offsets(hist), nbuckets, interpret)
    counts = jnp.sum(hist, axis=0)[:p]
    return rank.reshape(-1)[:n], counts
