"""On-device byte-stream tokenizer + word hasher.

The reference's map hot loop is a Lua ``gmatch("[^%s]+")`` per line with a
table-insert per token (examples/WordCount/mapfn.lua:4-7, job.lua:77-97).
The TPU-native version never materialises tokens: the raw UTF-8 bytes go to
the device as one ``[L] uint8`` array and a data-parallel pass computes,
per byte position,

  * whether a word ends there, and
  * the rolling 64-bit hash (two independent 32-bit polynomial lanes) of
    the word ending there, plus where its bytes start,

using an associative scan over affine maps — the standard trick for
sequential recurrences on parallel hardware: the rolling-hash step
``h_i = a*h_{i-1} + (b_i+1)`` is the affine map ``h -> m*h + c`` with
``(m, c) = (a, b_i+1)`` on word bytes and ``(0, 0)`` on separators (which
also performs the reset).  ``lax.associative_scan`` composes the maps in
O(log L) depth; the composed ``c`` lane at each position IS the hash of
the word-prefix ending there.

Note: FNV-1a itself (utils/hashing.py, the partition-hash parity fn) is
*not* scan-decomposable (xor-then-multiply is non-affine), so the device
path uses polynomial hashing.  Device and host paths agree because the
host twin here (`word_hashes_host`) implements the identical polynomial.

Hash equality stands in for string equality (64 bits: collision odds for a
1M-word vocabulary are ~3e-8); the final strings are materialised on the
host by slicing the original bytes at one representative (start, length)
per unique hash — the "hash on device, dictionary on host" answer to
string keys on a numeric accelerator (SURVEY.md §7 hard part (b)).

Whitespace = ASCII {space, \\t, \\n, \\r, \\f, \\v}, matching Python's
``str.split()`` on ASCII text (the reference's Lua ``%s`` class,
mapfn.lua:4-7); multi-byte UTF-8 sequences are treated as word bytes.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import pallas_compat

# jax.experimental.pallas is imported lazily inside the kernel/wrapper
# functions: this module rides every engine import, and processes that
# never select tokenize impl='pallas' should not pay the pallas import

#: polynomial multipliers for the two 32-bit hash lanes (odd constants:
#: FNV prime and a Murmur3 finalizer constant)
HASH_A1 = 16777619
HASH_A2 = 0x85EBCA6B
#: third, independent lane used only by collision-verify mode
HASH_A3 = 0xCC9E2D51
WORD_HASH_LANES = 2

_WS = (32, 9, 10, 13, 12, 11)


class TokenStream(NamedTuple):
    """Per-byte-position token info (fixed shape [L])."""

    is_end: jax.Array   # [L] bool — a word's last byte is here
    keys: jax.Array     # [L, 2] uint32 — hash lanes of the word ending here
    start: jax.Array    # [L] int32 — byte offset where that word starts
    length: jax.Array   # [L] int32 — word length in bytes


def _is_space(b: jax.Array) -> jax.Array:
    m = b == jnp.uint8(_WS[0])
    for w in _WS[1:]:
        m = m | (b == jnp.uint8(w))
    return m


def _affine_combine(left, right):
    ml, cl = left
    mr, cr = right
    return ml * mr, cl * mr + cr


#: inner tile width for the two-level scans.  A flat scan over millions of
#: elements costs log2(L) full-array passes; scanning [L/W, W] tiles along
#: the short axis + a small cross-tile prefix pass cuts the full-width
#: passes to log2(W) and keeps every intermediate a clean 2-D array.
SCAN_TILE = 512


def _shifted(x: jax.Array, d: int, fill) -> jax.Array:
    """x shifted right by d along its LAST axis, filling with *fill*."""
    pad = jnp.full(x.shape[:-1] + (d,), fill, x.dtype)
    return jnp.concatenate([pad, x[..., :-d]], axis=-1)


def _hillis_affine(m: jax.Array, c: jax.Array):
    """Inclusive scan of affine maps h->m*h+c along the last axis, as an
    UNROLLED Hillis-Steele ladder of log2(L) static shift+multiply-add
    passes.  jax.lax.associative_scan's recursive odd/even slicing
    compiles pathologically on TPU at multi-million-element widths
    (>10 min at L=4M, measured — the round-1 bench killer); this emits
    only pad/slice/mul/add HLO with static shapes, which XLA compiles in
    seconds and runs at HBM bandwidth."""
    L = m.shape[-1]
    d = 1
    while d < L:
        ml = _shifted(m, d, 1)
        cl = _shifted(c, d, 0)
        # compose right∘left BEFORE overwriting m: (m*ml, m*cl + c)
        m, c = m * ml, m * cl + c
        d *= 2
    return m, c


def _hillis_max(x: jax.Array) -> jax.Array:
    """Inclusive running max along the last axis (same ladder)."""
    L = x.shape[-1]
    lowest = (jnp.iinfo(x.dtype).min
              if jnp.issubdtype(x.dtype, jnp.integer) else -jnp.inf)
    d = 1
    while d < L:
        x = jnp.maximum(x, _shifted(x, d, lowest))
        d *= 2
    return x


def _affine_scan(m: jax.Array, c: jax.Array) -> jax.Array:
    """Inclusive scan of affine maps h->m*h+c; returns the composed c lane
    (== h at each position, with h before the sequence = 0).

    Two-level (tiled) formulation: within-tile inclusive scan vectorized
    over tiles, then an exclusive cross-tile prefix of the tile totals,
    composed back in — ``T_tile_i ∘ T_prefix_b = (Mi*Mp, Cp*Mi + Ci)``.
    """
    L = m.shape[0]
    W = SCAN_TILE
    if L % W != 0 or L <= W:
        _, c_out = _hillis_affine(m, c)
        return c_out
    mb = m.reshape(L // W, W)
    cb = c.reshape(L // W, W)
    Mi, Ci = _hillis_affine(mb, cb)
    # exclusive prefix of per-tile totals (last column), shifted by one
    Mt, Ct = Mi[:, -1], Ci[:, -1]
    Mp, Cp = _hillis_affine(Mt, Ct)
    one = jnp.ones((1,), m.dtype)
    zero = jnp.zeros((1,), c.dtype)
    Mp = jnp.concatenate([one, Mp[:-1]])
    Cp = jnp.concatenate([zero, Cp[:-1]])
    h = Cp[:, None] * Mi + Ci
    return h.reshape(L)


def _cummax_scan(x: jax.Array) -> jax.Array:
    """Tiled inclusive running max (same rationale as _affine_scan)."""
    L = x.shape[0]
    W = SCAN_TILE
    if L % W != 0 or L <= W:
        return _hillis_max(x)
    xb = x.reshape(L // W, W)
    inner = _hillis_max(xb)
    totals = inner[:, -1]
    prefix = _hillis_max(totals)
    lowest = jnp.full((1,), jnp.iinfo(x.dtype).min
                      if jnp.issubdtype(x.dtype, jnp.integer) else -jnp.inf,
                      x.dtype)
    prefix = jnp.concatenate([lowest, prefix[:-1]])
    return jnp.maximum(inner, prefix[:, None]).reshape(L)


# -- the fused Pallas tokenizing map-scan (tokenize_impl='pallas') -----------
#
# tokenize_hash's lax formulation pays, per hash lane, a log2-pass
# Hillis-Steele affine ladder over the full chunk, plus the boundary
# cummax ladder — each pass a full HBM read+write of the chunk-sized
# intermediates.  The kernel fuses byte classify + ALL affine-hash lanes
# + the word-boundary cummax into ONE blocked pass: per [R, 128] VMEM
# tile it composes the affine maps within-tile (two-level: lanes then
# rows) and threads the cross-block state — previous byte's space-ness,
# each hash lane's running value, the running word-start max — through
# kernel scratch across the sequential grid.  uint32 affine composition
# and int32 max are associative in machine arithmetic, so the result is
# BIT-identical to the ladder formulation (the golden suite pins it
# against the host oracle and the lax twin, including non-tile-multiple
# chunk lengths).

#: lane width of the tokenize kernel's 2-D layout
_TOK_LANES = 128
#: default bytes per kernel block (EngineConfig.tokenize_block
#: overrides and fingerprints it)
TOKENIZE_BLOCK = 4096
_INT32_MIN = -(2 ** 31)


def _tokenize_kernel(b_ref, nb_ref, *refs, multipliers: Tuple[int, ...],
                     R: int):
    """One grid step = one [R, _TOK_LANES] block of the byte chunk.
    refs: per-multiplier hash out-refs, then end/start/length out-refs
    (int32), then scratch: previous-byte space-ness (SMEM [1] i32),
    per-lane running hash (SMEM [n_lanes] u32), running word-start max
    (SMEM [1] i32)."""
    from jax.experimental import pallas as pl

    n_lanes = len(multipliers)
    h_refs = refs[:n_lanes]
    end_ref, start_ref, len_ref = refs[n_lanes:n_lanes + 3]
    cps_ref, ch_ref, cs_ref = refs[n_lanes + 3:]
    blk = pl.program_id(0)

    @pl.when(blk == 0)
    def _init():
        cps_ref[0] = jnp.int32(1)   # "the byte before the chunk is a
        for i in range(n_lanes):    # separator" (position 0 can start)
            ch_ref[i] = jnp.uint32(0)
        cs_ref[0] = jnp.int32(_INT32_MIN)

    b = b_ref[...]                  # [R, L] uint8
    space = _is_space(b)
    word = jnp.logical_not(space)
    next_space = _is_space(nb_ref[...])
    is_end = word & next_space
    # previous byte's space-ness, shifted in flattened order with the
    # cross-block carry at [0, 0]
    sp32 = space.astype(jnp.int32)
    prev_last = jnp.concatenate(
        [jnp.full((1, 1), cps_ref[0], jnp.int32), sp32[:-1, -1:]], axis=0)
    prev_space = jnp.concatenate([prev_last, sp32[:, :-1]], axis=1) > 0
    is_start = word & prev_space

    # the within-tile scans ARE the module's lax ladders (_hillis_affine
    # / _hillis_max): plain jnp code, identity-fill, exact — one
    # spelling shared by both formulations so they cannot drift
    L = b.shape[1]
    b32 = b.astype(jnp.uint32)
    for i, a in enumerate(multipliers):
        m = jnp.where(word, jnp.uint32(a), jnp.uint32(0))
        c = jnp.where(word, b32 + jnp.uint32(1), jnp.uint32(0))
        mw, cw = _hillis_affine(m, c)
        mi, ci = _hillis_affine(mw[None, :, -1], cw[None, :, -1])
        mi, ci = mi[0], ci[0]           # inclusive row-total composition
        hc = ch_ref[i]                  # running hash before this block
        comb = hc * mi + ci             # carry ∘ rows 0..r, value lane
        cp = jnp.concatenate(
            [jnp.broadcast_to(hc, (1,)).astype(jnp.uint32), comb[:-1]])
        h = cp[:, None] * mw + cw
        h_refs[i][...] = h
        ch_ref[i] = h[R - 1, L - 1]

    pos = (jnp.int32(blk) * jnp.int32(R * L)
           + jax.lax.broadcasted_iota(jnp.int32, (R, L), 0) * jnp.int32(L)
           + jax.lax.broadcasted_iota(jnp.int32, (R, L), 1))
    marks = jnp.where(is_start, pos, jnp.int32(-1))
    mw = _hillis_max(marks)
    rinc = _hillis_max(mw[None, :, -1])[0]
    cmax = cs_ref[0]
    pmax = jnp.concatenate(
        [jnp.broadcast_to(cmax, (1,)).astype(jnp.int32),
         jnp.maximum(rinc, cmax)[:-1]])
    start = jnp.maximum(mw, pmax[:, None])
    start_ref[...] = start
    len_ref[...] = pos - start + jnp.int32(1)
    end_ref[...] = is_end.astype(jnp.int32)
    cps_ref[0] = sp32[R - 1, L - 1]
    cs_ref[0] = start[R - 1, L - 1]


def _tokenize_pallas(chunk: jax.Array, multipliers: Tuple[int, ...],
                     block: int, interpret: Optional[bool]) -> TokenStream:
    """The fused kernel path behind :func:`tokenize_hash`
    (``impl='pallas'``) — identical TokenStream, one blocked pass."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    N = chunk.shape[0]
    L = _TOK_LANES
    block = max(L, (int(block) // L) * L)
    R = block // L
    npad = -(-N // block) * block
    pad = npad - N
    cp = (jnp.concatenate([chunk, jnp.full((pad,), ord(" "), jnp.uint8)])
          if pad else chunk)
    # next byte, space-filled at the end (matching the lax path's
    # next_space=True closure of the final word)
    nb = jnp.concatenate([cp[1:], jnp.full((1,), ord(" "), jnp.uint8)])
    rows = npad // L
    shape2 = (rows, L)
    spec = pl.BlockSpec((R, L), lambda i: (i, 0))
    n_lanes = len(multipliers)
    outs = pallas_compat.pallas_call(
        functools.partial(_tokenize_kernel,
                          multipliers=tuple(int(a) for a in multipliers),
                          R=R),
        name="tokenize",
        interpret=interpret,
        grid=(npad // block,),
        in_specs=[spec, spec],
        out_specs=[spec] * (n_lanes + 3),
        out_shape=[pallas_compat.sds(shape2, jnp.uint32, chunk)] * n_lanes
        + [pallas_compat.sds(shape2, jnp.int32, chunk)] * 3,
        scratch_shapes=[pltpu.SMEM((1,), jnp.int32),
                        pltpu.SMEM((n_lanes,), jnp.uint32),
                        pltpu.SMEM((1,), jnp.int32)],
    )(cp.reshape(shape2), nb.reshape(shape2))
    keys = jnp.stack([o.reshape(-1)[:N] for o in outs[:n_lanes]], axis=-1)
    end, start, length = (o.reshape(-1)[:N] for o in outs[n_lanes:])
    return TokenStream(is_end=end.astype(bool), keys=keys,
                       start=start, length=length)


def tokenize_hash(chunk: jax.Array,
                  multipliers=(HASH_A1, HASH_A2),
                  impl: str = "lax",
                  block: int = TOKENIZE_BLOCK,
                  interpret: Optional[bool] = None) -> TokenStream:
    """Tokenize one padded byte chunk ``[L] uint8`` entirely on-device.

    *multipliers* selects the polynomial hash lanes (one affine scan
    each); collision-verify mode passes a third lane.  ``impl`` picks
    the formulation: ``"lax"`` (the tiled Hillis-Steele ladders below)
    or ``"pallas"`` (ONE fused blocked kernel pass — classify + all
    hash lanes + boundary cummax together; bit-identical, pinned by the
    golden suite).  *block*/*interpret* configure the kernel only."""
    if impl not in ("lax", "pallas"):
        raise ValueError(f"tokenize impl must be 'lax' or 'pallas', "
                         f"got {impl!r}")
    if impl == "pallas":
        return _tokenize_pallas(chunk, tuple(multipliers), block,
                                interpret)
    L = chunk.shape[0]
    b32 = chunk.astype(jnp.uint32)
    space = _is_space(chunk)
    word = ~space

    # word ends: word byte whose successor is a separator (or the chunk end)
    next_space = jnp.concatenate([space[1:], jnp.ones((1,), bool)])
    is_end = word & next_space
    # word starts: word byte whose predecessor is a separator (or position 0)
    prev_space = jnp.concatenate([jnp.ones((1,), bool), space[:-1]])
    is_start = word & prev_space

    # independent polynomial hash lanes via one affine scan each
    keys = []
    for a in multipliers:
        m = jnp.where(word, jnp.uint32(a), jnp.uint32(0))
        c = jnp.where(word, b32 + jnp.uint32(1), jnp.uint32(0))
        keys.append(_affine_scan(m, c))
    keys = jnp.stack(keys, axis=-1)

    # start offset: running max of (position where a word starts, else -1),
    # reset implicitly because separators never read it
    pos = jnp.arange(L, dtype=jnp.int32)
    start_marks = jnp.where(is_start, pos, jnp.int32(-1))
    start = _cummax_scan(start_marks)
    length = pos - start + 1
    return TokenStream(is_end=is_end, keys=keys, start=start, length=length)


# --- host twin (oracle + final key materialisation) ------------------------

def word_hashes_host(text: bytes) -> dict:
    """Pure-Python twin of :func:`tokenize_hash`: {word_bytes: (h1, h2)}.
    Used by tests as the oracle and available for host-side fallback."""
    out = {}
    for w in text.split():
        h1 = h2 = 0
        for byte in w:
            h1 = (h1 * HASH_A1 + byte + 1) & 0xFFFFFFFF
            h2 = (h2 * HASH_A2 + byte + 1) & 0xFFFFFFFF
        out[w] = (h1, h2)
    return out


def shard_text(data: bytes, num_shards: int,
               pad_multiple: int = 128, return_offsets: bool = False,
               pad_to: int = None):
    """Host prep: split a text blob into ``num_shards`` roughly equal byte
    chunks on whitespace boundaries, space-padded to one common static
    length (multiple of *pad_multiple* for TPU lane alignment).

    ``pad_to`` fixes the padded length L to a caller-chosen value
    (still rounded to *pad_multiple*; raised if a span genuinely
    exceeds it): callers that compile shape-specialised programs pass a
    corpus-INDEPENDENT target so every corpus hits one compiled program
    / one persistent-cache entry, instead of a data-dependent max-span
    length that recompiles per corpus size.

    Returns ``(chunks [S, L] uint8, L)`` — or, with *return_offsets*,
    ``(chunks, L, starts [S] int64)`` where ``starts[i]`` is chunk *i*'s
    byte offset in *data* (so a padded-space offset ``c*L + j`` maps back
    to original offset ``starts[c] + j``).  Splitting only at whitespace
    keeps every word intact inside exactly one shard — the same invariant
    the reference gets from line-aligned input splits (README.md:43-45).
    """
    n = len(data)
    flat = np.frombuffer(data, dtype=np.uint8)  # zero-copy
    bounds = [0]
    for s in range(1, num_shards):
        cut = min(n, s * n // num_shards)
        while cut < n and data[cut:cut + 1] not in (b" ", b"\t", b"\n",
                                                    b"\r", b"\x0b", b"\x0c"):
            cut += 1
        bounds.append(cut)
    bounds.append(n)
    L = max(1, max(bounds[i + 1] - bounds[i] for i in range(num_shards)))
    if pad_to is not None:
        L = max(L, pad_to)
    L = ((L + pad_multiple - 1) // pad_multiple) * pad_multiple
    arr = np.full((num_shards, L), ord(" "), dtype=np.uint8)
    for i in range(num_shards):
        lo, hi = bounds[i], bounds[i + 1]
        arr[i, :hi - lo] = flat[lo:hi]  # single memcpy per shard
    if return_offsets:
        return arr, L, np.asarray(bounds[:-1], dtype=np.int64)
    return arr, L
