"""Unrolled log-step scans and sorted-run reduction for huge record arrays.

The aggregation core of the sort-hierarchy engine: after ``lax.sort``
groups equal keys into runs, everything else is O(N) elementwise work plus
O(log N) shifted passes — no scatters at record granularity, the operation
TPU XLA executes pathologically (measured ~100M el/s on v5e vs ~160M
rows/s for its tuned sort and near-peak elementwise throughput).

All scans here are Hillis-Steele ladders of STATIC shifts (pad + slice),
the same formulation as ops/tokenize: log2(N) full-array passes that XLA
compiles in seconds and runs at HBM bandwidth.  ``jnp.cumsum`` /
``associative_scan`` are avoided on multi-million-element arrays because
their recursive lowering compiles pathologically on TPU (>10 min at 4M,
measured in round 1).

``segmented_scan`` takes an ARBITRARY traceable associative ``op`` — this
is what lets the device path accept any user monoid, not just
{sum,min,max} (the compiler-visible form of the reference's
associative/commutative/idempotent reducer flags, reducefn.lua:10-14).
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp

#: sentinel key lane value marking invalid rows (sorts to the end);
#: real keys equal to the sentinel pair are remapped to (0, 0) — here and
#: at record-buffer build time (device_engine step) — so
#: (SENTINEL, SENTINEL) is unambiguous.
SENTINEL = jnp.uint32(0xFFFFFFFF)


def _shift_right(x: jax.Array, d: int, fill) -> jax.Array:
    pad = jnp.full(x.shape[:-1] + (d,), fill, x.dtype)
    return jnp.concatenate([pad, x[..., :-d]], axis=-1)


def ladder_cumsum(x: jax.Array) -> jax.Array:
    """Inclusive cumsum along the last axis (log2(N) shifted adds)."""
    L = x.shape[-1]
    d = 1
    while d < L:
        x = x + _shift_right(x, d, 0)
        d *= 2
    return x


def ladder_cummax(x: jax.Array) -> jax.Array:
    """Inclusive running max along the last axis."""
    L = x.shape[-1]
    lowest = (jnp.iinfo(x.dtype).min
              if jnp.issubdtype(x.dtype, jnp.integer) else -jnp.inf)
    d = 1
    while d < L:
        x = jnp.maximum(x, _shift_right(x, d, lowest))
        d *= 2
    return x


def segmented_scan(op: Callable, starts: jax.Array,
                   values: jax.Array) -> jax.Array:
    """Inclusive scan of *values* with *op*, restarting at each set bit of
    *starts* (segment heads).  ``op`` must be associative; values [N] or
    [N, D] (the ladder shifts along axis 0, so D lanes ride along).

    The classic segmented-combine is itself associative, so the ladder
    applies: ``(f_l, v_l) then (f, v) -> (f | f_l, f ? v : op(v_l, v))``.

    Precondition: ``starts[0]`` must be True unless the entire input is
    dead weight (positions before the first segment head produce junk —
    sorted_unique_reduce guarantees this by making row 0 a head).
    """
    N = starts.shape[0]
    f = starts
    v = values
    d = 1
    while d < N:
        f_l = jnp.concatenate([jnp.ones((d,), bool), f[:-d]])
        v_l = jnp.concatenate([v[:d], v[:-d]], axis=0)  # fill junk, masked
        blocked = f  # segment head: left neighbour is another segment
        combined = op(v_l, v)
        if v.ndim > 1:
            take = blocked[:, None] if v.ndim == 2 else blocked.reshape(
                (-1,) + (1,) * (v.ndim - 1))
        else:
            take = blocked
        v = jnp.where(take, v, combined)
        f = f | f_l
        d *= 2
    return v


class SortedUnique(NamedTuple):
    keys: jax.Array       # [capacity, 2] uint32, ascending among valid
    values: jax.Array     # [capacity, ...] run reductions
    payload: jax.Array    # [capacity, Q] representative payload (run end)
    valid: jax.Array      # [capacity] bool
    n_unique: jax.Array   # [] int32 (may exceed capacity: overflow signal)


def sorted_unique_reduce(keys: jax.Array, values, payload: jax.Array,
                         valid: jax.Array, capacity: int,
                         op, unit_values: bool = False,
                         rank_sort: bool = True,
                         sort_impl: str = "variadic") -> SortedUnique:
    """Group-by-key reduction for LARGE record batches: one sort, then
    shifted-compare run boundaries, a segmented scan (or run-length
    count when ``unit_values``), and gather-based compaction of the run
    ends — the only scatter-free group-by that runs at sort speed on TPU.

    ``op`` is a traceable associative fn ``(a, b) -> c`` or one of
    "sum" / "min" / "max".  With ``unit_values=True`` the values operand
    is ignored and each key's result is its occurrence count (int32) —
    the wordcount fast path, which also drops a sort operand.

    With ``rank_sort`` (the default) the sort carries only
    ``[k1, k2, iota]`` — three lanes whatever the value/payload arity —
    and the value/payload lanes are permuted afterwards by gathers.
    This decouples the ``lax.sort`` comparator (whose cold compile
    dominates the engine's cold compile at bench shapes and whose
    runtime grows with every carried operand) from the record width.
    ``lax.sort`` is stable, so the rank permutation reorders the lanes
    bit-identically to the variadic sort; ``rank_sort=False`` keeps the
    old variadic path for the golden-equivalence suite.

    ``sort_impl`` picks the permutation program itself:

    * ``"variadic"`` (default) — ONE 2-key sort of ``[k1, k2, ...]``
      (lane transport per ``rank_sort`` above); the steady-state
      tier-1 program: best runtime, worst comparator compile.
    * ``"argsort"`` — TWO stable 1-key sorts, each carrying only
      ``[key_lane, perm]``: sort by ``k2`` first, then stably by
      ``k1``.  ``lax.sort`` stability makes the composed permutation
      exactly the 2-key sort's permutation — equal-``k1`` rows keep
      ascending-``k2`` order, and equal ``(k1, k2)`` pairs keep input
      order — so the result is BIT-identical to the variadic path
      (the golden suite pins it).  The rank-sort trick applied to
      *compile* time: the comparator cost scales with num_keys ×
      operand count, and 1 key / 2 operands lowers ~3x faster than
      2 keys / 3 — the tier-0 program the tiered engine serves cold
      buckets on, at the cost of the extra permutation gathers
      (measured ~2.6x slower end to end at bench shapes, which is why
      it is a serving tier and not the steady state).
    """
    if sort_impl not in ("variadic", "argsort"):
        raise ValueError(f"sort_impl must be 'variadic' or 'argsort' "
                         f"here, got {sort_impl!r} (the 'tiered' policy "
                         "is resolved by the engine before tracing)")
    if isinstance(op, str):
        try:
            op = {"sum": jnp.add, "min": jnp.minimum,
                  "max": jnp.maximum}[op]
        except KeyError:
            raise ValueError(f"unknown reduce op {op!r}")
    N = keys.shape[0]
    # remap the (astronomically unlikely) real sentinel pair, then encode
    # invalid rows as the sentinel pair so they sort last
    is_sent = (keys[:, 0] == SENTINEL) & (keys[:, 1] == SENTINEL)
    k1 = jnp.where(is_sent, jnp.uint32(0), keys[:, 0])
    k2 = jnp.where(is_sent, jnp.uint32(0), keys[:, 1])
    k1 = jnp.where(valid, k1, SENTINEL)
    k2 = jnp.where(valid, k2, SENTINEL)

    Q = payload.shape[1]
    if unit_values:
        v2 = None
        n_val_lanes = 0
    else:
        v2 = values if values.ndim == 2 else values[:, None]
        n_val_lanes = v2.shape[1]
    if sort_impl == "argsort":
        # tier-0: two-pass stable argsort — each pass sorts ONE key
        # lane plus the running permutation (2 operands, 1 key), and
        # stability composes them into the exact 2-key permutation
        iota = jnp.arange(N, dtype=jnp.int32)
        _k2s, p1 = jax.lax.sort((k2, iota), num_keys=1)
        k1s, perm = jax.lax.sort((k1[p1], p1), num_keys=1)
        k2s = k2[perm]
        v2s = v2[perm] if n_val_lanes else None
        vals_s = [v2s[:, i] for i in range(n_val_lanes)]
        pay_s = payload[perm]
        pays_s = [pay_s[:, i] for i in range(Q)]
    elif rank_sort:
        iota = jnp.arange(N, dtype=jnp.int32)
        k1s, k2s, perm = jax.lax.sort((k1, k2, iota), num_keys=2)
        v2s = v2[perm] if n_val_lanes else None
        vals_s = [v2s[:, i] for i in range(n_val_lanes)]
        pay_s = payload[perm]
        pays_s = [pay_s[:, i] for i in range(Q)]
    else:
        pay_lanes = [payload[:, i] for i in range(Q)]
        val_lanes = [v2[:, i] for i in range(n_val_lanes)]
        sorted_ops = jax.lax.sort(tuple([k1, k2] + val_lanes + pay_lanes),
                                  num_keys=2)
        k1s, k2s = sorted_ops[0], sorted_ops[1]
        vals_s = list(sorted_ops[2:2 + len(val_lanes)])
        pays_s = list(sorted_ops[2 + len(val_lanes):])

    row_valid = ~((k1s == SENTINEL) & (k2s == SENTINEL))
    prev1 = _shift_right(k1s, 1, 0)
    prev2 = _shift_right(k2s, 1, 0)
    is_start = row_valid & ((k1s != prev1) | (k2s != prev2))
    # row 0 is always a segment head if valid (the shift fill of 0 would
    # otherwise miss a genuine leading (0,0) key)
    is_start = is_start.at[0].set(row_valid[0])
    next1 = jnp.concatenate([k1s[1:], jnp.zeros((1,), jnp.uint32)])
    next2 = jnp.concatenate([k2s[1:], jnp.zeros((1,), jnp.uint32)])
    is_end = row_valid & ((k1s != next1) | (k2s != next2)
                          | ~jnp.concatenate([row_valid[1:],
                                              jnp.zeros((1,), bool)]))
    is_end = is_end.at[-1].set(row_valid[-1])

    idx = jnp.arange(N, dtype=jnp.int32)
    if unit_values:
        run_start = ladder_cummax(jnp.where(is_start, idx, jnp.int32(-1)))
        reduced = [(idx - run_start + 1).astype(jnp.int32)]
    else:
        stacked = jnp.stack(vals_s, axis=-1) if len(vals_s) > 1 else vals_s[0]
        scanned = segmented_scan(op, is_start, stacked)
        reduced = ([scanned[:, i] for i in range(len(vals_s))]
                   if len(vals_s) > 1 else [scanned])

    # compact run ends by GATHER: searchsorted over the cumulative end
    # count finds the j-th run-end row (no O(N) scatter)
    end_csum = ladder_cumsum(is_end.astype(jnp.int32))
    n_unique = end_csum[-1] if N > 0 else jnp.int32(0)
    targets = jnp.arange(1, capacity + 1, dtype=jnp.int32)
    out_idx = jnp.searchsorted(end_csum, targets, side="left")
    out_idx = jnp.clip(out_idx, 0, N - 1)
    out_valid = targets <= n_unique

    out_keys = jnp.stack([k1s[out_idx], k2s[out_idx]], axis=-1)
    out_vals = [r[out_idx] for r in reduced]
    out_vals = (jnp.stack(out_vals, axis=-1) if len(out_vals) > 1
                else out_vals[0])
    out_pay = jnp.stack([p[out_idx] for p in pays_s], axis=-1)
    zero = jnp.zeros((), out_vals.dtype)
    out_vals = jnp.where(
        out_valid.reshape((-1,) + (1,) * (out_vals.ndim - 1)), out_vals,
        zero)
    out_keys = jnp.where(out_valid[:, None], out_keys, jnp.uint32(0))
    out_pay = jnp.where(out_valid[:, None], out_pay, jnp.int32(0))
    return SortedUnique(out_keys, out_vals, out_pay, out_valid,
                        n_unique.astype(jnp.int32))
