"""Unrolled log-step scans and sorted-run reduction for huge record arrays.

The aggregation core of the sort-hierarchy engine: after ``lax.sort``
groups equal keys into runs, everything else is O(N) elementwise work plus
O(log N) shifted passes — no scatters at record granularity, the operation
TPU XLA executes pathologically (measured ~100M el/s on v5e vs ~160M
rows/s for its tuned sort and near-peak elementwise throughput).

All scans here are Hillis-Steele ladders of STATIC shifts (pad + slice),
the same formulation as ops/tokenize: log2(N) full-array passes that XLA
compiles in seconds and runs at HBM bandwidth.  ``jnp.cumsum`` /
``associative_scan`` are avoided on multi-million-element arrays because
their recursive lowering compiles pathologically on TPU (>10 min at 4M,
measured in round 1).

``segmented_scan`` takes an ARBITRARY traceable associative ``op`` — this
is what lets the device path accept any user monoid, not just
{sum,min,max} (the compiler-visible form of the reference's
associative/commutative/idempotent reducer flags, reducefn.lua:10-14).

The post-sort stage also has a fused Pallas formulation
(``segment_impl='pallas'``, the ``_segreduce_kernel`` below): boundary
detection + segmented combine + run-end count in ONE VMEM-tiled pass
instead of the ladders' log2(N) full-array passes, bit-identical for
the engine's integer monoids and pinned by tests/test_pallas_ops.py.
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import pallas_compat

# jax.experimental.pallas is imported lazily inside the kernel/wrapper
# functions: this module rides every engine import, and processes that
# never select segment_impl='pallas' should not pay the pallas import

#: sentinel key lane value marking invalid rows (sorts to the end);
#: real keys equal to the sentinel pair are remapped to (0, 0) — here and
#: at record-buffer build time (device_engine step) — so
#: (SENTINEL, SENTINEL) is unambiguous.
SENTINEL = jnp.uint32(0xFFFFFFFF)
#: plain-int twin for Pallas kernel bodies (a module-level jnp constant
#: would be a captured traced array, which pallas_call refuses)
_SENT = np.uint32(0xFFFFFFFF)

#: lane width of the fused segmented-reduce kernel's 2-D layout (the
#: flattened record order is row-major over [rows, _SEG_LANES])
_SEG_LANES = 128
#: default elements per VMEM-tiled kernel block (multiple of _SEG_LANES;
#: EngineConfig.segment_block overrides and fingerprints it)
SEGMENT_BLOCK = 4096


def _shift_right(x: jax.Array, d: int, fill) -> jax.Array:
    pad = jnp.full(x.shape[:-1] + (d,), fill, x.dtype)
    return jnp.concatenate([pad, x[..., :-d]], axis=-1)


def ladder_cumsum(x: jax.Array) -> jax.Array:
    """Inclusive cumsum along the last axis (log2(N) shifted adds)."""
    L = x.shape[-1]
    d = 1
    while d < L:
        x = x + _shift_right(x, d, 0)
        d *= 2
    return x


def ladder_cummax(x: jax.Array) -> jax.Array:
    """Inclusive running max along the last axis."""
    L = x.shape[-1]
    lowest = (jnp.iinfo(x.dtype).min
              if jnp.issubdtype(x.dtype, jnp.integer) else -jnp.inf)
    d = 1
    while d < L:
        x = jnp.maximum(x, _shift_right(x, d, lowest))
        d *= 2
    return x


def segmented_scan(op: Callable, starts: jax.Array,
                   values: jax.Array) -> jax.Array:
    """Inclusive scan of *values* with *op*, restarting at each set bit of
    *starts* (segment heads).  ``op`` must be associative; values [N] or
    [N, D] (the ladder shifts along axis 0, so D lanes ride along).

    The classic segmented-combine is itself associative, so the ladder
    applies: ``(f_l, v_l) then (f, v) -> (f | f_l, f ? v : op(v_l, v))``.

    Precondition: ``starts[0]`` must be True unless the entire input is
    dead weight (positions before the first segment head produce junk —
    sorted_unique_reduce guarantees this by making row 0 a head).
    """
    N = starts.shape[0]
    f = starts
    v = values
    d = 1
    while d < N:
        f_l = jnp.concatenate([jnp.ones((d,), bool), f[:-d]])
        v_l = jnp.concatenate([v[:d], v[:-d]], axis=0)  # fill junk, masked
        blocked = f  # segment head: left neighbour is another segment
        combined = op(v_l, v)
        if v.ndim > 1:
            take = blocked[:, None] if v.ndim == 2 else blocked.reshape(
                (-1,) + (1,) * (v.ndim - 1))
        else:
            take = blocked
        v = jnp.where(take, v, combined)
        f = f | f_l
        d *= 2
    return v


# -- the fused Pallas segmented-reduce kernel (segment_impl='pallas') --------
#
# One VMEM-tiled pass over the sorted lanes replaces the lax ladder
# chain: run-boundary detection (shifted key compares, the previous/next
# element carried across blocks), the segmented combine (or run-length
# count), and the run-end cumulative count all happen per block, with
# the cross-block state — last key, running combine value, running end
# count — in kernel scratch that persists across the sequential grid.
# The lax formulation pays log2(N) full-array HBM passes per ladder
# (segmented_scan + ladder_cumsum + ladder_cummax); the kernel reads and
# writes each record once.  Bit-identity to the lax path holds for any
# integer monoid (the engine's contract): integer ops are associative in
# machine arithmetic, so the kernel's two-level association order
# produces identical bits, and the boundary/count lanes are exact by
# construction (the golden suite pins it, ops- and engine-level).


def _seg_ladder(flags: jax.Array, v: jax.Array, op: Callable):
    """Within-row inclusive segmented scan along axis 1 of ``v`` ([R, L]
    or [R, L, D]; *flags* [R, L]).  Returns ``(seen, v)``: ``seen[r, l]``
    = a flag exists in row r at or before lane l, ``v[r, l]`` = op-fold
    of row r from max(last flag, row start) through l.  Classic
    Hillis-Steele with a POSITIONAL guard (lanes < d are already
    complete) so unflagged row starts stay exact without an op
    identity."""
    lanes = flags.shape[1]
    lane = jax.lax.broadcasted_iota(jnp.int32, flags.shape, 1)
    stacked = v.ndim == 3

    def bsel(mask, a, b):
        return jnp.where(mask[..., None] if stacked else mask, a, b)

    f = flags
    seen = flags
    d = 1
    while d < lanes:
        f_l = jnp.concatenate(
            [jnp.ones(f.shape[:1] + (d,), bool), f[:, :-d]], axis=1)
        v_l = jnp.concatenate([v[:, :d], v[:, :-d]], axis=1)
        v = bsel(f | (lane < d), v, op(v_l, v))
        f = f | f_l
        seen = seen | jnp.concatenate(
            [jnp.zeros(seen.shape[:1] + (d,), bool), seen[:, :-d]], axis=1)
        d *= 2
    return seen, v


def _shift1_flat(x: jax.Array, carry) -> jax.Array:
    """*x* ([R, L]) shifted right by one in flattened row-major order;
    *carry* (the previous block's last element) fills position [0, 0]."""
    prev_last = jnp.concatenate(
        [jnp.full((1, 1), carry, x.dtype), x[:-1, -1:]], axis=0)
    return jnp.concatenate([prev_last, x[:, :-1]], axis=1)


def _cumsum_2level(e: jax.Array, carry) -> jax.Array:
    """Inclusive int32 cumsum of ``e`` ([R, L]) in flattened order,
    seeded by *carry* (zeros fill = exact identity)."""
    R, L = e.shape
    d = 1
    while d < L:
        e = e + jnp.concatenate(
            [jnp.zeros((R, d), jnp.int32), e[:, :-d]], axis=1)
        d *= 2
    rt = e[:, -1]
    d = 1
    while d < R:
        rt = rt + jnp.concatenate([jnp.zeros((d,), jnp.int32), rt[:-d]])
        d *= 2
    prefix = jnp.concatenate([jnp.zeros((1,), jnp.int32), rt[:-1]]) + carry
    return e + prefix[:, None]


def _segreduce_kernel(k1_ref, k2_ref, nk1_ref, nk2_ref, *refs,
                      op: Callable, n_lanes: int, unit: bool, R: int):
    """One grid step = one [R, _SEG_LANES] block of the sorted lanes.
    refs layout: n_lanes value in-refs (none when *unit*), then n_out
    reduced out-refs (1 when *unit*), csum out-ref, then scratch:
    carry keys (SMEM [2] u32), carry value (VMEM [1, n_out] value
    dtype), carry end-count (SMEM [1] i32)."""
    from jax.experimental import pallas as pl

    n_out = 1 if unit else n_lanes
    val_refs = () if unit else refs[:n_lanes]
    red_refs = refs[0 if unit else n_lanes:][:n_out]
    csum_ref = refs[(0 if unit else n_lanes) + n_out]
    ck_ref, cv_ref, cc_ref = refs[-3:]
    b = pl.program_id(0)

    @pl.when(b == 0)
    def _init():
        ck_ref[0] = _SENT
        ck_ref[1] = _SENT
        cv_ref[...] = jnp.zeros_like(cv_ref)
        cc_ref[0] = jnp.int32(0)

    k1 = k1_ref[...]
    k2 = k2_ref[...]
    valid = jnp.logical_not((k1 == _SENT) & (k2 == _SENT))
    pk1 = _shift1_flat(k1, ck_ref[0])
    pk2 = _shift1_flat(k2, ck_ref[1])
    is_start = valid & ((k1 != pk1) | (k2 != pk2))
    nk1 = nk1_ref[...]
    nk2 = nk2_ref[...]
    nvalid = jnp.logical_not((nk1 == _SENT) & (nk2 == _SENT))
    is_end = valid & ((k1 != nk1) | (k2 != nk2)
                      | jnp.logical_not(nvalid))

    if unit:
        v = jnp.ones(k1.shape, jnp.int32)
        op_eff = jnp.add
        stacked = False
    else:
        lanes = [r[...] for r in val_refs]
        stacked = n_lanes > 1
        v = jnp.stack(lanes, axis=-1) if stacked else lanes[0]
        op_eff = op
    seen, v = _seg_ladder(is_start, v, op_eff)
    # compose rows + the block carry: the within-row scan's last lane is
    # each row's (flag, value) summary; an exclusive prefix of those
    # summaries under the same segmented monoid — seeded by the carry
    # value in scratch — gives every row the value of the run continuing
    # into it from before
    rf = jnp.any(is_start, axis=1)
    rv = v[:, -1]                       # [R] or [R, D]
    r_seen, r_inc = _seg_ladder(rf[None, :],
                                rv[None, ...], op_eff)
    r_seen, r_inc = r_seen[0], r_inc[0]
    if stacked:
        carry_v = cv_ref[0, :]          # [D]
        comb = jnp.where(r_seen[:, None], r_inc,
                         op_eff(jnp.broadcast_to(carry_v, r_inc.shape),
                                r_inc))
        pv = jnp.concatenate([carry_v[None, :].astype(v.dtype),
                              comb[:-1]], axis=0)
        final = jnp.where(seen[..., None], v,
                          op_eff(jnp.broadcast_to(pv[:, None, :], v.shape),
                                 v))
        for i in range(n_out):
            red_refs[i][...] = final[..., i]
        cv_ref[0, :] = final[R - 1, _SEG_LANES - 1, :]
    else:
        carry_v = cv_ref[0, 0]
        comb = jnp.where(r_seen, r_inc,
                         op_eff(jnp.broadcast_to(carry_v, r_inc.shape),
                                r_inc))
        pv = jnp.concatenate(
            [jnp.broadcast_to(carry_v, (1,)).astype(v.dtype), comb[:-1]])
        final = jnp.where(seen, v,
                          op_eff(jnp.broadcast_to(pv[:, None], v.shape),
                                 v))
        red_refs[0][...] = final
        cv_ref[0, 0] = final[R - 1, _SEG_LANES - 1]

    csum = _cumsum_2level(is_end.astype(jnp.int32), cc_ref[0])
    csum_ref[...] = csum
    ck_ref[0] = k1[R - 1, _SEG_LANES - 1]
    ck_ref[1] = k2[R - 1, _SEG_LANES - 1]
    cc_ref[0] = csum[R - 1, _SEG_LANES - 1]


def _segment_reduce_pallas(k1s: jax.Array, k2s: jax.Array,
                           vals_s: Sequence[jax.Array], op: Callable,
                           unit_values: bool, block: int,
                           interpret: Optional[bool]):
    """The fused kernel path: returns ``(reduced_lanes, end_csum)`` over
    the sorted key/value lanes, matching the lax formulation bit for bit
    at every run-end position (the only rows the compaction gathers) and
    in the end count everywhere."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    N = k1s.shape[0]
    L = _SEG_LANES
    block = max(L, (int(block) // L) * L)
    R = block // L
    npad = -(-N // block) * block
    pad = npad - N

    def padded(x, fill):
        if not pad:
            return x
        return jnp.concatenate(
            [x, jnp.full((pad,) + x.shape[1:], fill, x.dtype)])

    k1p = padded(k1s, SENTINEL)
    k2p = padded(k2s, SENTINEL)
    # next-element key lanes: ONE elementwise shift each (vs the lax
    # ladders' log2(N) passes), SENTINEL-filled at the end so the last
    # real row is a run end exactly as the lax path forces it
    nk1 = jnp.concatenate([k1p[1:], jnp.full((1,), SENTINEL, jnp.uint32)])
    nk2 = jnp.concatenate([k2p[1:], jnp.full((1,), SENTINEL, jnp.uint32)])
    rows = npad // L
    shape2 = (rows, L)
    ins = [a.reshape(shape2) for a in (k1p, k2p, nk1, nk2)]
    if unit_values:
        n_lanes, n_out = 0, 1
        out_dtype = jnp.int32
    else:
        n_lanes = n_out = len(vals_s)
        # the scanned dtype the lax path would produce (a promoting
        # custom monoid widens it); integer promotion is exact, so
        # casting up front keeps bit-identity
        probe = jax.eval_shape(
            lambda a: op(a, a),
            jax.ShapeDtypeStruct((2, 2) if n_lanes == 1 else
                                 (2, 2, n_lanes), vals_s[0].dtype))
        out_dtype = probe.dtype
        ins += [padded(v, jnp.zeros((), v.dtype)).astype(out_dtype)
                .reshape(shape2) for v in vals_s]
    spec = pl.BlockSpec((R, L), lambda i: (i, 0))
    outs = pallas_compat.pallas_call(
        functools.partial(_segreduce_kernel, op=op, n_lanes=n_lanes,
                          unit=unit_values, R=R),
        name="segreduce",
        interpret=interpret,
        grid=(npad // block,),
        in_specs=[spec] * len(ins),
        out_specs=[spec] * (n_out + 1),
        out_shape=[pallas_compat.sds(shape2, out_dtype, k1s)] * n_out
        + [pallas_compat.sds(shape2, jnp.int32, k1s)],
        scratch_shapes=[pltpu.SMEM((2,), jnp.uint32),
                        pltpu.VMEM((1, max(n_out, 1)), out_dtype),
                        pltpu.SMEM((1,), jnp.int32)],
    )(*ins)
    reduced = [o.reshape(-1)[:N] for o in outs[:n_out]]
    end_csum = outs[n_out].reshape(-1)[:N]
    return reduced, end_csum


def _segment_reduce_lax(k1s: jax.Array, k2s: jax.Array,
                        vals_s: Sequence[jax.Array], op: Callable,
                        unit_values: bool):
    """The ladder formulation (shifted compares + segmented_scan /
    run-length cummax + ladder_cumsum) — the original reference path the
    kernel is pinned bit-identical to."""
    N = k1s.shape[0]
    row_valid = ~((k1s == SENTINEL) & (k2s == SENTINEL))
    prev1 = _shift_right(k1s, 1, 0)
    prev2 = _shift_right(k2s, 1, 0)
    is_start = row_valid & ((k1s != prev1) | (k2s != prev2))
    # row 0 is always a segment head if valid (the shift fill of 0 would
    # otherwise miss a genuine leading (0,0) key)
    is_start = is_start.at[0].set(row_valid[0])
    next1 = jnp.concatenate([k1s[1:], jnp.zeros((1,), jnp.uint32)])
    next2 = jnp.concatenate([k2s[1:], jnp.zeros((1,), jnp.uint32)])
    is_end = row_valid & ((k1s != next1) | (k2s != next2)
                          | ~jnp.concatenate([row_valid[1:],
                                              jnp.zeros((1,), bool)]))
    is_end = is_end.at[-1].set(row_valid[-1])

    idx = jnp.arange(N, dtype=jnp.int32)
    if unit_values:
        run_start = ladder_cummax(jnp.where(is_start, idx, jnp.int32(-1)))
        reduced = [(idx - run_start + 1).astype(jnp.int32)]
    else:
        stacked = (jnp.stack(vals_s, axis=-1) if len(vals_s) > 1
                   else vals_s[0])
        scanned = segmented_scan(op, is_start, stacked)
        reduced = ([scanned[..., i] for i in range(len(vals_s))]
                   if len(vals_s) > 1 else [scanned])
    end_csum = ladder_cumsum(is_end.astype(jnp.int32))
    return reduced, end_csum


class SortedUnique(NamedTuple):
    keys: jax.Array       # [capacity, 2] uint32, ascending among valid
    values: jax.Array     # [capacity, ...] run reductions
    payload: jax.Array    # [capacity, Q] representative payload (run end)
    valid: jax.Array      # [capacity] bool
    n_unique: jax.Array   # [] int32 (may exceed capacity: overflow signal)


def sorted_unique_reduce(keys: jax.Array, values, payload: jax.Array,
                         valid: jax.Array, capacity: int,
                         op, unit_values: bool = False,
                         rank_sort: bool = True,
                         sort_impl: str = "variadic",
                         segment_impl: str = "lax",
                         segment_block: int = SEGMENT_BLOCK,
                         interpret: Optional[bool] = None) -> SortedUnique:
    """Group-by-key reduction for LARGE record batches: one sort, then
    shifted-compare run boundaries, a segmented scan (or run-length
    count when ``unit_values``), and gather-based compaction of the run
    ends — the only scatter-free group-by that runs at sort speed on TPU.

    ``op`` is a traceable associative fn ``(a, b) -> c`` or one of
    "sum" / "min" / "max".  With ``unit_values=True`` the values operand
    is ignored and each key's result is its occurrence count (int32) —
    the wordcount fast path, which also drops a sort operand.

    With ``rank_sort`` (the default) the sort carries only
    ``[k1, k2, iota]`` — three lanes whatever the value/payload arity —
    and the value/payload lanes are permuted afterwards by gathers.
    This decouples the ``lax.sort`` comparator (whose cold compile
    dominates the engine's cold compile at bench shapes and whose
    runtime grows with every carried operand) from the record width.
    ``lax.sort`` is stable, so the rank permutation reorders the lanes
    bit-identically to the variadic sort; ``rank_sort=False`` keeps the
    old variadic path for the golden-equivalence suite.

    ``sort_impl`` picks the permutation program itself:

    * ``"variadic"`` (default) — ONE 2-key sort of ``[k1, k2, ...]``
      (lane transport per ``rank_sort`` above); the steady-state
      tier-1 program: best runtime, worst comparator compile.
    * ``"argsort"`` — TWO stable 1-key sorts, each carrying only
      ``[key_lane, perm]``: sort by ``k2`` first, then stably by
      ``k1``.  ``lax.sort`` stability makes the composed permutation
      exactly the 2-key sort's permutation — equal-``k1`` rows keep
      ascending-``k2`` order, and equal ``(k1, k2)`` pairs keep input
      order — so the result is BIT-identical to the variadic path
      (the golden suite pins it).  The rank-sort trick applied to
      *compile* time: the comparator cost scales with num_keys ×
      operand count, and 1 key / 2 operands lowers ~3x faster than
      2 keys / 3 — the tier-0 program the tiered engine serves cold
      buckets on, at the cost of the extra permutation gathers
      (measured ~2.6x slower end to end at bench shapes, which is why
      it is a serving tier and not the steady state).
    * ``"radix"`` — NO comparator: the Pallas LSD radix sort of
      ops/radix_sort (4-bit digits, 16 passes over the 64-bit key),
      bit-identical to the variadic permutation (the golden suite pins
      it); record lanes always use the rank-sort gather transport.
      The comparator lowering — the dominant cold-compile cost —
      disappears entirely from the program.

    ``segment_impl`` picks the post-sort segmented-reduce formulation:

    * ``"lax"`` (default) — the ladder chain above: shifted-compare
      boundaries + segmented_scan / run-length cummax + ladder_cumsum,
      each a log2(N)-pass Hillis-Steele over the full arrays;
    * ``"pallas"`` — ONE fused VMEM-tiled kernel pass over the sorted
      lanes (run-boundary detection, segmented combine or run-length
      count, and the run-end cumulative count together, cross-block
      state in kernel scratch), bit-identical to ``"lax"`` for the
      engine's integer monoids (the golden suite pins it).  *
      ``segment_block`` sets the kernel's elements-per-block tile;
      ``interpret=None`` auto-selects the Pallas interpreter off-TPU
      (ops/pallas_compat — CPU runs validate semantics, not speed).
      The run-end compaction below is gather-based either way and is
      shared verbatim between the two implementations.
    """
    if sort_impl not in ("variadic", "argsort", "radix"):
        raise ValueError(f"sort_impl must be 'variadic', 'argsort' or "
                         f"'radix' here, got {sort_impl!r} (the tiered "
                         "policies are resolved by the engine before "
                         "tracing)")
    if segment_impl not in ("lax", "pallas"):
        raise ValueError(f"segment_impl must be 'lax' or 'pallas', "
                         f"got {segment_impl!r}")
    if isinstance(op, str):
        try:
            op = {"sum": jnp.add, "min": jnp.minimum,
                  "max": jnp.maximum}[op]
        except KeyError:
            raise ValueError(f"unknown reduce op {op!r}")
    N = keys.shape[0]
    # remap the (astronomically unlikely) real sentinel pair, then encode
    # invalid rows as the sentinel pair so they sort last
    is_sent = (keys[:, 0] == SENTINEL) & (keys[:, 1] == SENTINEL)
    k1 = jnp.where(is_sent, jnp.uint32(0), keys[:, 0])
    k2 = jnp.where(is_sent, jnp.uint32(0), keys[:, 1])
    k1 = jnp.where(valid, k1, SENTINEL)
    k2 = jnp.where(valid, k2, SENTINEL)

    Q = payload.shape[1]
    if unit_values:
        v2 = None
        n_val_lanes = 0
    else:
        v2 = values if values.ndim == 2 else values[:, None]
        n_val_lanes = v2.shape[1]
    if sort_impl == "argsort":
        # tier-0: two-pass stable argsort — each pass sorts ONE key
        # lane plus the running permutation (2 operands, 1 key), and
        # stability composes them into the exact 2-key permutation
        iota = jnp.arange(N, dtype=jnp.int32)
        _k2s, p1 = jax.lax.sort((k2, iota), num_keys=1)
        k1s, perm = jax.lax.sort((k1[p1], p1), num_keys=1)
        k2s = k2[perm]
        v2s = v2[perm] if n_val_lanes else None
        vals_s = [v2s[:, i] for i in range(n_val_lanes)]
        pay_s = payload[perm]
        pays_s = [pay_s[:, i] for i in range(Q)]
    elif sort_impl == "radix":
        # no comparator at all: Pallas LSD radix over the hash-key lanes
        # (ops/radix_sort), bit-identical to the variadic permutation;
        # record lanes always ride the rank-sort gather transport
        from .radix_sort import radix_sort_pairs
        k1s, k2s, perm = radix_sort_pairs(k1, k2, interpret=interpret)
        v2s = v2[perm] if n_val_lanes else None
        vals_s = [v2s[:, i] for i in range(n_val_lanes)]
        pay_s = payload[perm]
        pays_s = [pay_s[:, i] for i in range(Q)]
    elif rank_sort:
        iota = jnp.arange(N, dtype=jnp.int32)
        k1s, k2s, perm = jax.lax.sort((k1, k2, iota), num_keys=2)
        v2s = v2[perm] if n_val_lanes else None
        vals_s = [v2s[:, i] for i in range(n_val_lanes)]
        pay_s = payload[perm]
        pays_s = [pay_s[:, i] for i in range(Q)]
    else:
        pay_lanes = [payload[:, i] for i in range(Q)]
        val_lanes = [v2[:, i] for i in range(n_val_lanes)]
        sorted_ops = jax.lax.sort(tuple([k1, k2] + val_lanes + pay_lanes),
                                  num_keys=2)
        k1s, k2s = sorted_ops[0], sorted_ops[1]
        vals_s = list(sorted_ops[2:2 + len(val_lanes)])
        pays_s = list(sorted_ops[2 + len(val_lanes):])

    if segment_impl == "pallas":
        reduced, end_csum = _segment_reduce_pallas(
            k1s, k2s, vals_s, op, unit_values, segment_block, interpret)
    else:
        reduced, end_csum = _segment_reduce_lax(
            k1s, k2s, vals_s, op, unit_values)

    # compact run ends by GATHER: searchsorted over the cumulative end
    # count finds the j-th run-end row (no O(N) scatter).  Shared
    # verbatim between the two segment_impls, so the kernel's
    # equivalence surface is exactly (reduced lanes, end_csum).
    n_unique = end_csum[-1] if N > 0 else jnp.int32(0)
    targets = jnp.arange(1, capacity + 1, dtype=jnp.int32)
    out_idx = jnp.searchsorted(end_csum, targets, side="left")
    out_idx = jnp.clip(out_idx, 0, N - 1)
    out_valid = targets <= n_unique

    out_keys = jnp.stack([k1s[out_idx], k2s[out_idx]], axis=-1)
    out_vals = [r[out_idx] for r in reduced]
    out_vals = (jnp.stack(out_vals, axis=-1) if len(out_vals) > 1
                else out_vals[0])
    out_pay = jnp.stack([p[out_idx] for p in pays_s], axis=-1)
    zero = jnp.zeros((), out_vals.dtype)
    out_vals = jnp.where(
        out_valid.reshape((-1,) + (1,) * (out_vals.ndim - 1)), out_vals,
        zero)
    out_keys = jnp.where(out_valid[:, None], out_keys, jnp.uint32(0))
    out_pay = jnp.where(out_valid[:, None], out_pay, jnp.int32(0))
    return SortedUnique(out_keys, out_vals, out_pay, out_valid,
                        n_unique.astype(jnp.int32))
