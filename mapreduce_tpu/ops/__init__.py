"""Device-side primitive ops for the TPU data plane.

These replace the reference's per-record host work — Lua-table emits,
``table.sort`` on keys (job.lua:194), heap-based k-way merge
(utils.lua:206-271) — with batched, statically-shaped XLA programs:
segmented sort/reduce over hashed keys, and a byte-stream tokenizer+hasher
that turns raw text into (hash, payload) records without any host loop.
All shapes are static and padding is explicit (valid masks), keeping
everything jit/shard_map-compatible (SURVEY.md §7 hard part (a)).
"""

from .compaction import tile_compact  # noqa: F401
from .pallas_compat import default_interpret, pick_block  # noqa: F401
from .segscan import (  # noqa: F401
    SEGMENT_BLOCK, SENTINEL, ladder_cummax, ladder_cumsum,
    segmented_scan, sorted_unique_reduce)
from .tokenize import (  # noqa: F401
    TOKENIZE_BLOCK, WORD_HASH_LANES, tokenize_hash)
