"""Shared Pallas plumbing: ONE spelling of the CPU-fallback policy.

Every Pallas kernel in this repo (ops/flash_attention.py and the fused
wave-program hot-path kernels in ops/segscan.py / ops/tokenize.py) wants
the same three pieces of glue, previously duplicated inside
flash_attention:

* **interpret-mode default** — ``interpret = jax.default_backend() !=
  "tpu"``: compiled Mosaic on a real TPU, the Pallas interpreter
  everywhere else, so the tier-1 CPU test mesh executes the REAL kernel
  logic (grid sequencing, scratch carries, block index maps) rather
  than a shadow jnp implementation.  Interpret-mode numbers validate
  semantics, never speed.
* **block-size fitting** — :func:`pick_block` shrinks a requested block
  to one that divides the dimension and satisfies Mosaic's sublane
  rule, so ANY shape works without the caller raising.
* **vma-aware out shapes** — :func:`sds` builds ShapeDtypeStructs that
  inherit an exemplar's varying-mesh-axes set, so a kernel composes
  with ``shard_map``'s vma checking (the kernels are purely per-device:
  outputs vary exactly as their inputs do).

:func:`pallas_call` is the thin entry point the kernel modules dispatch
through: it resolves the interpret default in ONE place, forwards an
optional ``pl.CostEstimate`` hint, and counts kernel-program traces in
the metrics registry (``mrtpu_pallas_kernel_builds_total``).  The count
is TRACE-time: compiles and abstract shape probes (the engine's
``jax.eval_shape`` aval derivations reach here too) both increment it,
while warm executable-cache dispatches add nothing — so a nonzero delta
is the registry witness that a config actually routes through the
kernel programs (what the bench smoke asserts), not a count of XLA
kernel compiles.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax

from ..obs import metrics as _obs

# NOTE: jax.experimental.pallas is imported lazily inside
# :func:`pallas_call` — this module rides every package import (ops/
# __init__), and the suite spawns many short-lived subprocesses that
# never build a kernel; they should not pay the pallas import.

_KERNEL_BUILDS = _obs.counter(
    "mrtpu_pallas_kernel_builds_total",
    "Pallas kernel programs traced (labels: kernel, "
    "mode=interpret|mosaic) — a trace-time count: incremented whenever "
    "an enclosing program traces the kernel (compiles AND abstract "
    "shape probes like the engine's eval_shape aval derivations), zero "
    "on warm executable-cache dispatches.  A nonzero delta therefore "
    "witnesses 'this config routes through the kernel', not 'XLA "
    "compiled N kernels'")


def default_interpret(interpret: Optional[bool] = None) -> bool:
    """THE interpret-mode policy: compiled Mosaic on TPU, the Pallas
    interpreter everywhere else (``None`` = auto).  An explicit bool
    wins — tests force either mode deterministically."""
    if interpret is None:
        return jax.default_backend() != "tpu"
    return bool(interpret)


def pick_block(t: int, want: int) -> int:
    """Largest block <= *want* that divides *t* and satisfies Mosaic's
    sublane rule (multiple of 8, or the whole dimension).  Falls back to
    the smallest valid divisor above *want* (worst case *t* itself, one
    VMEM-resident tile) so ANY dimension works — a shape that ran on the
    jnp path must not start raising here."""
    if t <= want:
        return t
    for b in range(want, 7, -1):
        if t % b == 0 and b % 8 == 0:
            return b
    for b in range(want + 1, t):
        if t % b == 0 and (b % 8 == 0 or b == t):
            return b
    return t


def sds(shape: Sequence[int], dtype: Any, like: Any) -> jax.ShapeDtypeStruct:
    """ShapeDtypeStruct inheriting *like*'s varying-mesh-axes set, so the
    kernel composes with shard_map's vma checking (the kernel is purely
    per-device: outputs vary exactly as its inputs do)."""
    try:
        vma = jax.typeof(like).vma
    except AttributeError:  # pragma: no cover - older jax
        vma = None
    if vma:
        return jax.ShapeDtypeStruct(tuple(shape), dtype, vma=vma)
    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def pallas_call(kernel, *, name: str, interpret: Optional[bool] = None,
                cost_estimate: Optional[Any] = None, **kwargs):
    """``pl.pallas_call`` with the repo-wide CPU-fallback policy applied
    and the build counted (*name* labels the kernel family in
    ``mrtpu_pallas_kernel_builds_total``).  *cost_estimate* forwards a
    ``pl.CostEstimate`` scheduling hint when the caller has one."""
    from jax.experimental import pallas as pl  # lazy: see module note

    interp = default_interpret(interpret)
    _KERNEL_BUILDS.inc(kernel=name,
                       mode="interpret" if interp else "mosaic")
    if cost_estimate is not None:
        kwargs["cost_estimate"] = cost_estimate
    return pl.pallas_call(kernel, name=name, interpret=interp, **kwargs)
