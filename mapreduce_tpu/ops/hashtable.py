"""Scatter-based hash-table aggregation: group-by-key without sorting.

XLA's on-device sort is the wrong tool for aggregating tens of millions of
records (measured on v5e: ~1.7s AND ~60s of compile per 2M-row sort); the
TPU-native answer is a vectorized open-addressing hash table driven
entirely by scatter/gather, so cost is O(records) memory traffic and only
*unique* keys (thousands, not millions) ever reach a sort:

  round j of K:
    slot  = (h1 + j*(h2|1)) mod B          (double hashing)
    claim = scatter-set own key into empty slots (conflicts: one arbitrary
            winner per slot — XLA scatter semantics)
    match = gather slot key == own key
    fold  = scatter-add/min/max own value where matched
    survivors carry to round j+1

Identical keys share a probe sequence, so every record of a key either
folds into the table or ALL of them are left over — leftovers are
guaranteed disjoint from the table's keys, which lets callers union
``compact(table)`` with a (small, sorted) combine of the leftovers without
a final dedup pass.  Collisions never corrupt counts: a record folds only
after key equality is verified by gather.

This is the combiner/reducer engine stage (the role job.lua:196-215 and
utils.lua:206-271 fill with Lua table sorts and a heap merge); the sort
path (segmented.py) remains for small inputs and ordered output.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from .segmented import REDUCE_OPS, Combined, combine_by_key

#: empty-slot marker (a real 64-bit key equal to the sentinel is remapped
#: to 0 at insert, as in the native host core mr_native.cpp)
SENTINEL = jnp.uint32(0xFFFFFFFF)


class HashTable(NamedTuple):
    keys: jax.Array     # [B, 2] uint32; SENTINEL/SENTINEL = empty
    values: jax.Array   # [B, ...] monoid accumulator
    payload: jax.Array  # [B, Q] representative payload


def _value_init(shape, dtype, op: str):
    if op == "sum":
        return jnp.zeros(shape, dtype)
    big = (jnp.iinfo(dtype).max if jnp.issubdtype(dtype, jnp.integer)
           else jnp.inf)
    return jnp.full(shape, big if op == "min" else -big, dtype)


def empty_table(n_buckets: int, value_shape: Tuple[int, ...], value_dtype,
                payload_shape: Tuple[int, ...], payload_dtype,
                op: str = "sum") -> HashTable:
    if op not in REDUCE_OPS:
        raise ValueError(f"op must be one of {REDUCE_OPS}, got {op!r}")
    return HashTable(
        keys=jnp.full((n_buckets, 2), SENTINEL, jnp.uint32),
        values=_value_init((n_buckets,) + tuple(value_shape), value_dtype,
                           op),
        payload=jnp.zeros((n_buckets,) + tuple(payload_shape),
                          payload_dtype),
    )


def table_insert(table: HashTable, keys: jax.Array, values: jax.Array,
                 payload: jax.Array, valid: jax.Array,
                 n_rounds: int = 4, op: str = "sum",
                 ) -> Tuple[HashTable, jax.Array]:
    """Fold a record batch into *table*; returns ``(table, leftover)``
    where ``leftover`` marks records that found no slot in n_rounds (their
    keys are provably absent from the table — see module docstring)."""
    B = table.keys.shape[0]
    # remap the (astronomically unlikely) sentinel key to 0
    is_sent = (keys[:, 0] == SENTINEL) & (keys[:, 1] == SENTINEL)
    keys = jnp.where(is_sent[:, None], jnp.uint32(0), keys)
    h1 = keys[:, 0]
    stride = keys[:, 1] | jnp.uint32(1)  # odd => probes stay distinct

    tab_keys, tab_vals, tab_pay = table
    pending = valid
    for j in range(n_rounds):
        slot = ((h1 + jnp.uint32(j) * stride) % jnp.uint32(B)).astype(
            jnp.int32)
        stored = tab_keys[slot]  # [N, 2]
        empty = (stored[:, 0] == SENTINEL) & (stored[:, 1] == SENTINEL)
        writers = pending & empty
        # claim: one arbitrary writer per slot wins; drop non-writers
        wslot = jnp.where(writers, slot, B)
        tab_keys = tab_keys.at[wslot].set(keys, mode="drop")
        stored = tab_keys[slot]  # re-gather post-claim
        mine = (stored[:, 0] == keys[:, 0]) & (stored[:, 1] == keys[:, 1])
        matched = pending & mine
        mslot = jnp.where(matched, slot, B)
        if op == "sum":
            tab_vals = tab_vals.at[mslot].add(values, mode="drop")
        elif op == "min":
            tab_vals = tab_vals.at[mslot].min(values, mode="drop")
        else:
            tab_vals = tab_vals.at[mslot].max(values, mode="drop")
        # any matching record's payload is a valid representative
        tab_pay = tab_pay.at[mslot].set(payload, mode="drop")
        pending = pending & ~matched
    return HashTable(tab_keys, tab_vals, tab_pay), pending


def table_compact(table: HashTable, capacity: int) -> Combined:
    """Occupied buckets -> dense Combined (unsorted; n_unique > capacity
    signals overflow like combine_by_key)."""
    occupied = ~((table.keys[:, 0] == SENTINEL)
                 & (table.keys[:, 1] == SENTINEL))
    idx = jnp.cumsum(occupied.astype(jnp.int32)) - 1
    n = occupied.sum().astype(jnp.int32)
    idx = jnp.where(occupied, idx, capacity)

    def pack(arr, fill=0):
        buf = jnp.full((capacity,) + arr.shape[1:], fill, arr.dtype)
        return buf.at[idx].set(arr, mode="drop")

    return Combined(
        keys=pack(table.keys),
        values=pack(table.values),
        payload=pack(table.payload),
        valid=jnp.arange(capacity) < jnp.minimum(n, capacity),
        n_unique=n,
    )


def aggregate_disjoint(keys, values, payload, valid, n_buckets: int,
                       capacity: int, leftover_capacity: int,
                       op: str = "sum", n_rounds: int = 4):
    """One-shot group-by: hash-table fold + sorted combine of the (rare)
    leftovers.  Returns ``(table_part, leftover_part, overflow)`` — two
    Combined batches with DISJOINT key sets whose concatenation is the
    exact aggregation of the input."""
    table = empty_table(n_buckets, values.shape[1:], values.dtype,
                        payload.shape[1:], payload.dtype, op)
    table, leftover = table_insert(table, keys, values, payload, valid,
                                   n_rounds, op)
    main = table_compact(table, capacity)
    rest = combine_by_key(keys, values, payload, leftover,
                          leftover_capacity, op)
    overflow = (jnp.maximum(main.n_unique - capacity, 0)
                + jnp.maximum(rest.n_unique - leftover_capacity, 0))
    return main, rest, overflow
