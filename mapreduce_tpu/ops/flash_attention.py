"""In-tree Pallas flash attention: the transformer's single-chip hot op.

Why a hand-written kernel (the first Pallas use in this repo, and a
measured one): the unchunked jnp attention materialises the [B, H, T, T]
f32 score tensor in HBM — at the bench config (B4 H16 T2048) that is
1.07GB *per layer* re-read across softmax passes, measured 9% of peak on
v5e (scratch/prof_mfu.py); the lax.scan + jax.checkpoint flash tiling
(parallel/ring.py block path) keeps memory bounded but pays scan
overhead + full recompute, topping out at 34% step MFU
(scratch/prof_mfu2.py).  A Pallas kernel holds each score tile in VMEM,
never touching HBM with scores at all (measured: scratch/prof_flash3.py).

Kernel layout is ``[B, H, T, D]`` (Mosaic tiling wants the sequence and
head_dim in the last two block dims); the wrapper accepts the model's
native ``[B, T, H, D]`` too and transposes, but the transformer feeds
the kernel layout directly so no transpose is ever materialised.  The
grid is ``(B, H, T/block_q, T/block_kv)`` — KV innermost, so the
(m, den, acc) online-softmax state for one Q tile lives in VMEM scratch
across KV steps while Pallas double-buffers the KV tile DMAs against the
MXU.  Causal Q tiles skip above-diagonal KV tiles entirely — the index
map redirects the skipped DMA to the next tile that will be needed (the
shipped-kernel trick), so neither FLOPs nor bytes are wasted.  Score
memory is O(block_q x block_kv) whatever T is, so the same kernel serves
the 2048-token bench and the 32K long-context config.

Backward is the standard two-pass flash recomputation (dQ pass over KV
tiles, dKV pass over Q tiles) wired through ``jax.custom_vjp`` with
(q, k, v, out, lse) residuals — activation memory O(B T H D), never
O(T²).  lse/delta ride as ``[B, H, T, 1]`` so their tiles obey lane
tiling without 128x replication.

The reference has no analogue (its only notion of long inputs is
streaming file iterators, utils.lua:133-200); this is the beyond-parity
long-context family's hot op (SURVEY.md §7 "pallas kernels for the hot
ops").
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_compat import default_interpret, pallas_call, pick_block, sds

NEG_INF = -1e30  # large-negative instead of -inf: keeps exp/max NaN-free

#: shared plumbing lives in ops/pallas_compat (ONE spelling of the
#: CPU-fallback policy across every kernel module); the old private
#: names stay as aliases for in-tree callers of the kernel internals
_pick_block = pick_block
_sds = sds


def _on_diag(iq, j, block_q, block_kv):
    """Does KV tile j intersect or precede Q tile iq's causal row range?"""
    return j * block_kv <= iq * block_q + block_q - 1


# -- forward -----------------------------------------------------------------


def _crosses_diag(iq, j, block_q, block_kv):
    """Does KV tile j contain any masked (above-diagonal) element for Q
    tile iq?  False for tiles strictly below the diagonal — those run
    the unmasked body, skipping the iota/compare/select VPU passes that
    dominate a VPU-bound kernel (the MXU work per tile is ~4us; 31/32 of
    a 32K causal grid's needed tiles never cross the diagonal)."""
    return j * block_kv + block_kv - 1 > iq * block_q


def _dispatch_tile(accum, needed, causal, iq, j, block_q, block_kv):
    """Run *accum(mask)* under the masked/full split all three kernels
    share: diagonal-crossing tiles take the masked body, strictly-below
    tiles the unmasked one, non-causal always unmasked."""
    if not causal:
        accum(False)
        return
    diag = _crosses_diag(iq, j, block_q, block_kv)

    @pl.when(needed & diag)
    def _tile_masked():
        accum(True)

    @pl.when(needed & jnp.logical_not(diag))
    def _tile_full():
        accum(False)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, den_scr, acc_scr,
                *, causal, block_q, block_kv, n_kv):
    # q arrives PRE-SCALED by 1/sqrt(D) (see _fwd_call): one elementwise
    # pass over [B,H,T,D] outside replaces a [block_q,block_kv] scale
    # pass in every tile
    iq = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        den_scr[...] = jnp.zeros_like(den_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q_start = iq * block_q
    k_start = j * block_kv
    needed = _on_diag(iq, j, block_q, block_kv) if causal else True

    def _accum(mask):
        q = q_ref[0, 0]  # [block_q, D]
        k = k_ref[0, 0]  # [block_kv, D]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if mask:
            qp = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0)
            kp = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1)
            s = jnp.where(kp <= qp, s, NEG_INF)
        m_prev = m_scr[:, 0:1]                      # [block_q, 1]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                      # masked cols -> 0
        corr = jnp.exp(m_prev - m_new)
        den = den_scr[:, 0:1] * corr + p.sum(axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)     # [block_q, D]
        acc_scr[...] = acc_scr[...] * corr + pv
        m_scr[:, 0:1] = m_new
        den_scr[:, 0:1] = den

    _dispatch_tile(_accum, needed, causal, iq, j, block_q, block_kv)

    # emit once, on the final KV step (the j-loop keeps (m, den, acc) in
    # VMEM scratch; dividing every step cost a [block_q, D] divide + log
    # per tile for values that never left VMEM)
    @pl.when(j == n_kv - 1)
    def _emit():
        den = jnp.maximum(den_scr[:, 0:1], 1e-30)
        o_ref[0, 0] = (acc_scr[...] / den).astype(o_ref.dtype)
        lse_ref[0, 0] = m_scr[:, 0:1] + jnp.log(den)


# -- backward: dQ pass -------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_scr, *, scale, causal, block_q, block_kv, n_kv):
    # q is pre-scaled (q^ = q/sqrt(D)); the kernel accumulates dq^ = ds.k
    # and the one final emission multiplies by scale (chain rule through
    # q^ = scale*q), replacing a per-tile [block_q, D] scale pass
    iq = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    q_start = iq * block_q
    k_start = j * block_kv
    needed = _on_diag(iq, j, block_q, block_kv) if causal else True

    def _accum(mask):
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]             # [block_q, 1]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if mask:
            qp = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0)
            kp = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1)
            s = jnp.where(kp <= qp, s, NEG_INF)
        p = jnp.exp(s - lse)            # recomputed softmax tile
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)           # [block_q, block_kv] f32
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    _dispatch_tile(_accum, needed, causal, iq, j, block_q, block_kv)

    @pl.when(j == n_kv - 1)
    def _emit():
        dq_ref[0, 0] = (dq_scr[...] * scale).astype(dq_ref.dtype)


# -- backward: dK/dV pass ----------------------------------------------------


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr,
                *, causal, block_q, block_kv, n_q):
    # q is pre-scaled, so dK = dS^T . q^ needs NO scale factor at all
    # (dk = dS^T . scale*q exactly)
    jk = pl.program_id(2)
    i = pl.program_id(3)

    @pl.when(i == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    q_start = i * block_q
    k_start = jk * block_kv
    needed = (q_start + block_q - 1 >= k_start) if causal else True

    def _accum(mask):
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if mask:
            qp = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0)
            kp = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1)
            s = jnp.where(kp <= qp, s, NEG_INF)
        p = jnp.exp(s - lse)            # [block_q, block_kv]
        # dV += P^T . dO   (contract over the q axis)
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        # dK += dS^T . Q^
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    _dispatch_tile(_accum, needed, causal, i, jk, block_q, block_kv)

    @pl.when(i == n_q - 1)
    def _emit():
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


# -- pallas_call wrappers ----------------------------------------------------


def _q_index(b, h, i, j):
    return (b, h, i, 0)


def _make_kv_index(causal, block_q, block_kv, n_kv):
    def kv_index(b, h, i, j):
        if not causal:
            return (b, h, j, 0)
        # skipped (above-diagonal) tiles redirect their DMA to tile 0 —
        # the first tile the NEXT Q block will need — so no bytes stream
        # for tiles the kernel won't touch
        return (b, h, jax.lax.select(
            _on_diag(i, j, block_q, block_kv), j, 0), 0)
    return kv_index


def _fwd_call(q, k, v, cfgt):
    causal, scale, block_q, block_kv, interpret = cfgt
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    n_q, n_kv = Tq // block_q, Tk // block_kv
    q = (q.astype(jnp.float32) * scale).astype(q.dtype)  # q^ = q/sqrt(D)
    kv_index = _make_kv_index(causal, block_q, block_kv, n_kv)
    q_spec = pl.BlockSpec((1, 1, block_q, D), _q_index)
    kv_spec = pl.BlockSpec((1, 1, block_kv, D), kv_index)
    row_spec = pl.BlockSpec((1, 1, block_q, 1), _q_index)
    kernel = functools.partial(
        _fwd_kernel, causal=causal,
        block_q=block_q, block_kv=block_kv, n_kv=n_kv)
    out, lse = pallas_call(
        kernel,
        name="flash_fwd",
        grid=(B, H, n_q, n_kv),
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=[q_spec, row_spec],
        out_shape=[_sds(q.shape, q.dtype, q),
                   _sds((B, H, Tq, 1), jnp.float32, q)],
        scratch_shapes=[pltpu.VMEM((block_q, 128), jnp.float32),
                        pltpu.VMEM((block_q, 128), jnp.float32),
                        pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v)
    return out, lse


def _bwd_call(q, k, v, out, lse, do, cfgt, dlse=None):
    causal, scale, block_q, block_kv, interpret = cfgt
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    n_q, n_kv = Tq // block_q, Tk // block_kv
    # the kernels recompute s from the PRE-SCALED q^ (matching _fwd_call's
    # lse); dq picks scale back up at emission, dk needs none (dk=dS^T.q^)
    q = (q.astype(jnp.float32) * scale).astype(q.dtype)
    # delta[b,h,t] = sum_d dO * O — a tiny elementwise pass, jnp is fine
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [B, H, Tq, 1]
    if dlse is not None:
        # lse cotangent: ds += p * dlse == running the same kernels with
        # delta - dlse (see _flash_lse_bwd)
        delta = delta - dlse.astype(jnp.float32)

    kv_index = _make_kv_index(causal, block_q, block_kv, n_kv)
    q_spec = pl.BlockSpec((1, 1, block_q, D), _q_index)
    kv_spec = pl.BlockSpec((1, 1, block_kv, D), kv_index)
    row_spec = pl.BlockSpec((1, 1, block_q, 1), _q_index)

    dq = pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_kv=block_kv, n_kv=n_kv),
        name="flash_dq",
        grid=(B, H, n_q, n_kv),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=_sds(q.shape, q.dtype, q),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dKV grid: KV tiles outer, Q tiles inner; causal skips tiles fully
    # BELOW the needed range, redirecting to the last Q tile (always
    # needed: it is on/after every diagonal)
    def q_index2(b, h, j, i):
        if not causal:
            return (b, h, i, 0)
        return (b, h, jax.lax.select(
            i * block_q + block_q - 1 >= j * block_kv, i, n_q - 1), 0)

    def kv_index2(b, h, j, i):
        return (b, h, j, 0)

    q_spec2 = pl.BlockSpec((1, 1, block_q, D), q_index2)
    kv_spec2 = pl.BlockSpec((1, 1, block_kv, D), kv_index2)
    row_spec2 = pl.BlockSpec((1, 1, block_q, 1), q_index2)
    dk, dv = pallas_call(
        functools.partial(_dkv_kernel, causal=causal,
                          block_q=block_q, block_kv=block_kv, n_q=n_q),
        name="flash_dkv",
        grid=(B, H, n_kv, n_q),
        in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, row_spec2,
                  row_spec2],
        out_specs=[kv_spec2, kv_spec2],
        out_shape=[_sds(k.shape, k.dtype, k),
                   _sds(v.shape, v.dtype, v)],
        scratch_shapes=[pltpu.VMEM((block_kv, D), jnp.float32),
                        pltpu.VMEM((block_kv, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash_lse(q, k, v, cfgt):
    return _fwd_call(q, k, v, cfgt)


def _flash_lse_fwd(q, k, v, cfgt):
    out, lse = _fwd_call(q, k, v, cfgt)
    return (out, lse), (q, k, v, out, lse)


def _flash_lse_bwd(cfgt, res, cots):
    """Backward with BOTH cotangents: the lse cotangent folds into the
    delta term — d(lse)/ds is the softmax row p, so ds picks up p*dlse,
    i.e. the kernels run unchanged with delta' = delta - dlse.  (dv has
    no lse term: lse is independent of V.)  flash_attention discards
    lse, so its dlse arrives as zeros and the fold is a no-op there."""
    q, k, v, out, lse = res
    do, dlse = cots
    return _bwd_call(q, k, v, out, lse, do, cfgt, dlse=dlse)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def _make_cfgt(q, k, causal, scale, block_q, block_kv, interpret):
    D = q.shape[3]
    if scale is None:
        scale = D ** -0.5
    block_q = pick_block(q.shape[2], block_q)
    block_kv = pick_block(k.shape[2], block_kv)
    return (bool(causal), float(scale), int(block_q), int(block_kv),
            default_interpret(interpret))


def flash_attention_lse(q: jax.Array, k: jax.Array, v: jax.Array, *,
                        causal: bool = True,
                        scale: Optional[float] = None,
                        block_q: int = 1024, block_kv: int = 1024,
                        interpret: Optional[bool] = None):
    """Kernel-layout (``[B, H, T, D]``) attention returning
    ``(out, lse [B, H, T, 1] f32)`` — the partial-softmax form ring
    attention needs to combine per-ring-step results across devices
    (parallel/ring.py); fully differentiable including through uses of
    lse.  Same tiling/auto-shrink rules as :func:`flash_attention`."""
    cfgt = _make_cfgt(q, k, causal, scale, block_q, block_kv, interpret)
    return _flash_lse(q, k, v, cfgt)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True, scale: Optional[float] = None,
                    block_q: int = 1024, block_kv: int = 1024,
                    layout: str = "bhtd",
                    interpret: Optional[bool] = None) -> jax.Array:
    """Tiled attention, differentiable; O(block²) score memory.

    ``layout="bhtd"`` (kernel-native) or ``"bthd"`` (the ring path's
    convention; transposed in and out).  ``interpret=None`` auto-selects
    the Pallas interpreter off-TPU (the CPU test mesh) and the compiled
    Mosaic kernel on TPU.  Block sizes shrink to T when T is smaller;
    T must divide by the (shrunk) blocks.
    """
    if layout == "bthd":
        q, k, v = (jnp.swapaxes(a, 1, 2) for a in (q, k, v))
    elif layout != "bhtd":
        raise ValueError(f"unknown layout {layout!r}")
    cfgt = _make_cfgt(q, k, causal, scale, block_q, block_kv, interpret)
    out, _ = _flash_lse(q, k, v, cfgt)
    if layout == "bthd":
        out = jnp.swapaxes(out, 1, 2)
    return out
