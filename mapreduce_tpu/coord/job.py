"""Job executor: one claimed map or reduce job.

Parity with mapreduce/job.lua: load the user module (process-cached), build
the ``emit`` closure, run the user fn, then for map — sort + combine +
partition + write per-partition record files (job_prepare_map,
job.lua:154-228); for reduce — k-way merge all mappers' files for one
partition and fold each key (job_prepare_reduce, job.lua:230-296) — writing
status transitions and cpu/real timings back into the job document
(job.lua:117-152).

Intended-behavior decisions where the reference is quirky (SURVEY.md §7):

  * worker-side ``init`` receives the real ``init_args`` (the reference
    passes an undefined global — job.lua:369);
  * the combiner is the explicitly-configured ``combinerfn`` param; when
    absent and the reduce module declares itself associative + commutative
    + idempotent, ``reducefn`` doubles as the combiner (what the reference
    examples do by hand, reducefn.lua:10-14) — a non-ACI reducefn is never
    silently used as a combiner (the reference would, task.lua:322-327).
"""

from __future__ import annotations

import concurrent.futures
import logging
import re
import time
import urllib.parse
from typing import Any, Callable, Dict, List, Optional

from .. import spec
from ..obs import metrics as _metrics
from ..obs.trace import TRACER
from ..utils.constants import (
    STATUS, TASK_STATUS, MAX_MAP_RESULT, MAP_RESULT_TEMPLATE)
from ..utils.iterators import merge_iterator
from ..utils.serialization import (
    serialize_record, sort_key, check_serializable)
from .. import storage as storage_mod
from . import docstore
from .connection import Connection

logger = logging.getLogger("mapreduce_tpu.coord.job")

# -- per-task accounting: the collector's roll-up substrate (task = the
#    task database name — low cardinality by construction) and the skew
#    inputs obs/analysis reads.  ``partition`` is bounded by
#    num_reducers; map-side increments measure SHUFFLE VOLUME INTO each
#    partition, which is exactly what partition-skew diagnosis wants. ---
_TASK_RECORDS = _metrics.counter(
    "mrtpu_task_records_total",
    "record lines written by jobs, per task (labels: task, phase)")
_TASK_BYTES = _metrics.counter(
    "mrtpu_task_bytes_total",
    "record bytes written by jobs, per task (labels: task, phase)")
_PARTITION_RECORDS = _metrics.counter(
    "mrtpu_partition_records_total",
    "records routed into each reduce partition at map write time plus "
    "records reduced out of it (labels: task, phase, partition)")
_PARTITION_BYTES = _metrics.counter(
    "mrtpu_partition_bytes_total",
    "record bytes per reduce partition (labels: task, phase, partition)")


def sanitize_token(s: str) -> str:
    """Make an arbitrary key string safe inside a blob name."""
    return urllib.parse.quote(str(s), safe="")


def map_file_name(ns: str, part: int, mapkey: Any) -> str:
    """``<ns>.P<part>.M<mapkey>`` (reference job.lua:196-215), partition
    zero-padded so lexicographic listing groups deterministically."""
    return MAP_RESULT_TEMPLATE.format(
        ns=ns, part=f"{part:05d}", mapkey=sanitize_token(mapkey))


def map_results_prefix(path: str) -> str:
    """The shared map-output namespace for a task (single source of truth
    for job writers and the server's reduce planner)."""
    return f"{path}/map_results"


def ambient_scope(connection: Connection, storage_dsl) -> set:
    """The ``HOST:PORT`` endpoints a job's ambient auth token is valid
    for: its own board and its own http storage — nothing else, so user
    fns dialing third-party HTTP hosts cannot leak the cluster secret."""
    from ..utils.httpclient import split_embedded_token

    hosts = set()
    # every replica of a multi-endpoint (HA) board is this job's own
    # board: a claim that failed over mid-job still carries its auth
    hosts.update(connection.board_hostports())
    # parse the DSL prefix directly: get_storage_from would mkdtemp as a
    # side effect for a bare "shared" string
    if isinstance(storage_dsl, str) and storage_dsl.startswith("http:"):
        hosts.add(split_embedded_token(storage_dsl.partition(":")[2])[1])
    return hosts


class Job:
    """Reference: ``job(cnn, job_tbl, task_status, fname, init_args, ...)``
    (job.lua:300-381); instances are built by the worker from a claimed
    job document plus the task singleton's fields."""

    def __init__(self, connection: Connection, job_tbl: Dict[str, Any],
                 task_status: TASK_STATUS, task_tbl: Dict[str, Any],
                 jobs_ns: str, fence: Optional[Any] = None) -> None:
        self._cnn = connection
        self.tbl = job_tbl
        self.task_status = task_status
        self.task_tbl = task_tbl
        self.jobs_ns = jobs_ns
        #: threading.Event set by the worker's heartbeat thread when this
        #: claim's lease is confirmed lost; checked at every emit and
        #: before each output-publish / write-back step, so a fenced run
        #: aborts instead of racing the re-issued copy.  A publish
        #: already in flight when the fence drops may still land (benign:
        #: per-job-named atomic whole-content files); the hard guarantee
        #: is the claim-guarded job-document write-back.
        self._fence = fence
        self._storage = storage_mod.router(
            task_tbl["storage"], auth=connection.auth_token(),
            retry=getattr(connection, "retry_policy", None))
        self.path = task_tbl["path"]
        #: files consumed by a reduce run, deleted only once WRITTEN is
        #: durable (a re-run of a crashed reduce must still find them)
        self._consumed: List[str] = []

    # -- status transitions (job.lua:117-152, 322-342) --------------------

    def get_id(self) -> str:
        return self.tbl["_id"]

    def _claim_query(self) -> Dict[str, Any]:
        """Match the job only while THIS claim still owns it.  A worker
        whose lease was reaped and whose job was reclaimed by someone else
        must not clobber the new owner's state (the reference has exactly
        this hazard and shrugs, task.lua:307-309)."""
        return {"_id": self.get_id(),
                "worker": self.tbl.get("worker"),
                "tmpname": self.tbl.get("tmpname")}

    def _set_status(self, status: STATUS,
                    extra: Optional[Dict] = None) -> bool:
        fields = {"status": int(status)}
        if extra:
            fields.update(extra)
        n = self._cnn.connect().update(self.jobs_ns, self._claim_query(),
                                       {"$set": fields})
        return n > 0

    def mark_as_finished(self) -> bool:
        return self._set_status(STATUS.FINISHED,
                                {"finished_time": docstore.now()})

    def mark_as_written(self, cpu_time: float, real_time: float) -> bool:
        return self._set_status(STATUS.WRITTEN,
                                {"written_time": docstore.now(),
                                 "cpu_time": cpu_time,
                                 "real_time": real_time})

    def mark_as_broken(self) -> None:
        """BROKEN + $inc repetitions; claimable again (job.lua:322-342).
        Guarded by the claim so a stale worker can't re-break a job another
        worker has since reclaimed, and by status so a post-completion
        failure (e.g. cleanup I/O) can never demote a durably WRITTEN job
        back to claimable."""
        self._cnn.connect().update(
            self.jobs_ns,
            {**self._claim_query(),
             "status": {"$nin": [int(STATUS.WRITTEN),
                                 int(STATUS.FAILED)]}},
            {"$set": {"status": int(STATUS.BROKEN)},
             "$inc": {"repetitions": 1}})

    def _check_fence(self) -> None:
        """Abort if the heartbeat thread has confirmed lease loss; called
        from emit (so a long user fn dies at its next emission) and before
        each output-visibility step."""
        if self._fence is not None and self._fence.is_set():
            from .task import LeaseLostError
            raise LeaseLostError(
                f"job {self.get_id()}: lease lost (reaped or reclaimed); "
                "aborting this run — the re-issued copy owns the job now")

    # -- user-fn plumbing --------------------------------------------------

    def _role(self, role: str) -> spec.RoleModule:
        rm = spec.load_role(self.task_tbl[role], role)
        rm.ensure_init(self.task_tbl.get("init_args"))
        return rm

    def _effective_combiner(self) -> Optional[Callable]:
        name = self.task_tbl.get("combinerfn")
        if name:
            return self._role("combinerfn").fn
        red = self._role("reducefn")
        if spec.is_aci(red):
            return lambda k, vs: red.fn(k, vs)
        return None

    # -- execution ---------------------------------------------------------

    def execute(self) -> None:
        """job:__call dispatch (job.lua:345-381).  Runs under the ambient
        auth token — scoped to this job's own board + storage endpoints —
        so user map/reduce fns that build their own storage handle
        (router(DSL) in module code, e.g. examples/train_digits) inherit
        the worker's --auth without env/DSL plumbing."""
        from ..utils.httpclient import push_ambient_auth, restore_ambient_auth

        # durations on the monotonic clock: an NTP step mid-job must not
        # corrupt the persisted real_time (started_time/written_time stay
        # wall-clock by contract — they are timestamps, not durations)
        t_cpu, t_real = time.process_time(), time.monotonic()
        prev_auth = push_ambient_auth(
            self._cnn.auth_token(),
            ambient_scope(self._cnn, self.task_tbl.get("storage")))
        try:
            if self.task_status == TASK_STATUS.MAP:
                self._execute_map()
            elif self.task_status == TASK_STATUS.REDUCE:
                self._execute_reduce()
            else:
                raise RuntimeError(
                    f"job in task status {self.task_status}")
        finally:
            restore_ambient_auth(prev_auth)
        self._check_fence()
        owned = self.mark_as_written(time.process_time() - t_cpu,
                                     time.monotonic() - t_real)
        # delete consumed map files only once WRITTEN is durable AND this
        # claim still owned the job (a reaped+reclaimed job's files belong
        # to the new owner's re-run); reference deletes pre-write,
        # job.lua:293, which loses the partition if the worker dies between
        # build and write-back.  A cleanup failure must NOT escape: the job
        # is already durably WRITTEN, and letting a storage blip bubble to
        # the worker's shield would demote a completed job to BROKEN — a
        # forced duplicate execution whose inputs may be partially deleted.
        if owned and self._consumed:
            try:
                self._storage.remove_many(self._consumed)
            except OSError:
                logger.warning(
                    "job %s: WRITTEN but consumed-input cleanup failed; "
                    "leaving orphan map files behind", self.get_id(),
                    exc_info=True)
        self._consumed = []

    def _execute_map(self) -> None:
        """job_prepare_map (job.lua:154-228)."""
        mapfn = self._role("mapfn").fn
        partfn = self._role("partitionfn").fn
        combiner = self._effective_combiner()

        result: Dict[Any, List[Any]] = {}
        keyorder: Dict[Any, Any] = {}
        # sort_key memo for the scalar keys real workloads emit: emit is
        # THE map hot loop and sort_key allocates a rank tuple per call.
        # Two type-split caches, because dict keys compare by value across
        # types (True == 1 == 1.0) while sort_key ranks them differently —
        # and only exact str/int (not bool, not float) are cached, so a
        # float key can never alias an int cache entry.
        _sk_str: Dict[str, Any] = {}
        _sk_int: Dict[int, Any] = {}

        def emit(key: Any, value: Any) -> None:
            self._check_fence()
            tk = type(key)
            if tk is str:
                sk = _sk_str.get(key)
                if sk is None:
                    sk = _sk_str[key] = sort_key(key)
            elif tk is int:
                sk = _sk_int.get(key)
                if sk is None:
                    sk = _sk_int[key] = sort_key(key)
            else:
                sk = sort_key(key)
            bucket = result.setdefault(sk, [])
            keyorder.setdefault(sk, key)
            bucket.append(value)
            # streaming combine: collapse a hot key's pending values
            # (job.lua:92-96, threshold utils.lua:53)
            if combiner is not None and len(bucket) >= MAX_MAP_RESULT:
                result[sk] = [combiner(key, bucket)]

        with TRACER.span("run", phase="map", job=self.get_id()):
            mapfn(self.tbl["key"], self.tbl["value"], emit)
            self.mark_as_finished()

            # sort keys, write-time combine, partition (job.lua:194-215)
            per_part: Dict[int, List[str]] = {}
            for sk in sorted(result.keys()):
                key = keyorder[sk]
                values = result[sk]
                if combiner is not None and len(values) > 1:
                    values = [combiner(key, values)]
                part = partfn(key)
                if not isinstance(part, int):
                    raise TypeError(
                        f"partitionfn must return int, got "
                        f"{type(part).__name__}"
                        " (reference job.lua:203-207)")
                per_part.setdefault(part, []).append(
                    serialize_record(key, values))

        with TRACER.span("write", phase="map", job=self.get_id(),
                         partitions=len(per_part)):
            ns = map_results_prefix(self.path)
            db = self._cnn.dbname
            for part, lines in per_part.items():
                nb = sum(len(ln) for ln in lines)
                part_lbl = f"P{part:05d}"
                _PARTITION_RECORDS.inc(len(lines), task=db, phase="map",
                                       partition=part_lbl)
                _PARTITION_BYTES.inc(nb, task=db, phase="map",
                                     partition=part_lbl)
                _TASK_RECORDS.inc(len(lines), task=db, phase="map")
                _TASK_BYTES.inc(nb, task=db, phase="map")

            def put_one(part: int, lines: List[str]) -> None:
                self._check_fence()
                b = self._storage.builder()
                for line in lines:
                    b.write_record_line(line)
                b.build(map_file_name(ns, part, self.get_id()))

            items = list(per_part.items())
            if len(items) > 1 and self._storage.scheme == "http":
                # fan the per-partition PUTs out over the blob client's
                # connection pool instead of serializing ~num_reducers
                # round trips on one socket; local backends gain nothing
                # from threads, so they keep the serial loop
                with concurrent.futures.ThreadPoolExecutor(
                        max_workers=min(len(items), 8)) as ex:
                    futs = [ex.submit(put_one, part, lines)
                            for part, lines in items]
                    for f in futs:
                        f.result()  # first failure (incl. a fence) raises
            else:
                for part, lines in items:
                    put_one(part, lines)

    def _execute_reduce(self) -> None:
        """job_prepare_reduce (job.lua:230-296): merge all mappers' files
        for one partition, fold keys, write one result file."""
        red = self._role("reducefn")
        reducefn, aci = red.fn, spec.is_aci(red)
        value = self.tbl["value"]
        file_prefix, result_name = value["file"], value["result"]

        files = self._storage.list(
            "^" + re.escape(file_prefix) + r"\.M")
        sources = [
            (lambda name: lambda: _records(self._storage, name))(n)
            for n in files
        ]
        b = self._storage.builder()
        n_out = 0
        out_bytes = 0
        with TRACER.span("run", phase="reduce", job=self.get_id(),
                         inputs=len(files)):
            for key, values in merge_iterator(sources):
                self._check_fence()
                # ACI fast path: a single value needs no reduce call
                # (job.lua:264-284)
                if aci and len(values) == 1:
                    out = values[0]
                else:
                    out = reducefn(key, values)
                check_serializable(out)
                line = serialize_record(key, [out])
                n_out += 1
                out_bytes += len(line)
                b.write_record_line(line)
        with TRACER.span("write", phase="reduce", job=self.get_id()):
            b.build(result_name)
        db = self._cnn.dbname
        # the reduce job id IS the partition token (P<nnnnn>)
        _PARTITION_RECORDS.inc(n_out, task=db, phase="reduce",
                               partition=str(self.get_id()))
        _PARTITION_BYTES.inc(out_bytes, task=db, phase="reduce",
                             partition=str(self.get_id()))
        _TASK_RECORDS.inc(n_out, task=db, phase="reduce")
        _TASK_BYTES.inc(out_bytes, task=db, phase="reduce")
        # deletion of consumed inputs is deferred to execute(), post-WRITTEN
        self._consumed = files


def _records(storage, name):
    from ..utils.serialization import parse_record
    for line in storage.open_lines(name):
        yield parse_record(line)


def run_map_inline(task_tbl: Dict[str, Any], key: Any, value: Any,
                   emit: Callable[[Any, Any], None]) -> None:
    """Run a mapfn outside the job machinery (used by tests/tools)."""
    rm = spec.load_role(task_tbl["mapfn"], "mapfn")
    rm.ensure_init(task_tbl.get("init_args"))
    rm.fn(key, value, emit)
