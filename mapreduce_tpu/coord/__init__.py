"""Control plane: the reference's MongoDB role, rebuilt host-side.

The reference uses MongoDB collections as a polled job board and singleton
task document (SURVEY.md §2.11): ``<db>.task``, ``<db>.map_jobs``,
``<db>.red_jobs``, ``<db>.errors`` (task.lua:349-352, cnn.lua:55-71).  The
rebuild keeps the same document/collection *model* — it is a good fit for a
dynamic job board — but backs it with in-process memory (unit tests,
single-process mode) or a shared directory (multi-process workers), and
strengthens the two weak points the survey calls out: claims are truly
atomic (``find_and_modify``) and RUNNING jobs carry a lease so dead workers
are reaped (reference has neither, task.lua:294-309 FIXMEs, SURVEY.md §5).
"""

from .docstore import MemoryDocStore, DirDocStore, connect  # noqa: F401
from .docserver import DocServer, HttpDocStore  # noqa: F401
from .connection import Connection  # noqa: F401
from .task import Task  # noqa: F401
from .lease import (  # noqa: F401
    BoardLease, TrainerFencedError, TrainerLease)
from .ha import HaController, ReplicatedDocStore  # noqa: F401
from .job import Job  # noqa: F401
from .persistent_table import PersistentTable  # noqa: F401
