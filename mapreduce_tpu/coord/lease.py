"""Trainer lease: fenced single-writer election through the job board.

The host plane's preemption story (PR 1) is lease + heartbeat + fence on
every job claim (coord/task.py).  Training needs the same shape at a
different granularity: ONE writer may advance the optimizer state at a
time, a preempted/partitioned trainer must FENCE at its next step
boundary (never committing a checkpoint a successor could race), and a
successor must take over the moment the lease is free — immediately on
clean release, after expiry on silent death.

Implementation: a singleton lease document in ``<db>.trainer_lease`` on
the same DocStore the job board rides (mem/dir/http all work), mutated
only through atomic guarded updates:

  * :meth:`try_acquire` — ``find_and_modify`` guarded by "free or
    expired"; every successful acquire increments ``generation``, the
    fencing token (a successor's generation is strictly greater, so a
    stale holder can prove it was superseded);
  * :meth:`heartbeat` — guarded lease extension, same contract as
    ``Task.heartbeat``: False is KNOWLEDGE of loss (the answer arrived
    over a working RPC), a transport error proves nothing either way;
  * :meth:`ensure_owned` — the step-boundary gate ``fit`` calls:
    retries transport errors (ownership unknown) until a definitive
    answer, raises :class:`TrainerFencedError` on loss;
  * :meth:`release` — clean handoff: holder cleared, expiry zeroed, so
    the successor's acquire succeeds on its next poll with NO reap
    wait (the ``Task.release_jobs`` semantic for the training plane).
"""

from __future__ import annotations

import os
import socket
import time
import uuid
from typing import Any, Dict, Optional

from ..obs import metrics as _metrics
from . import docstore
from .connection import Connection
from .task import LeaseLostError

#: default trainer lease (seconds) — epochs are the beat cadence, so
#: this must comfortably exceed one epoch + one checkpoint write.
DEFAULT_TRAINER_LEASE = 15.0

_ACQUIRES = _metrics.counter(
    "mrtpu_trainer_lease_acquires_total",
    "trainer-lease acquisition attempts (labels: outcome=acquired|busy)")
_BEATS = _metrics.counter(
    "mrtpu_trainer_lease_beats_total",
    "trainer-lease heartbeats (labels: outcome=owned|lost|error)")
_FENCES = _metrics.counter(
    "mrtpu_trainer_lease_fences_total",
    "times a trainer fenced itself after losing its lease")
_GENERATION = _metrics.gauge(
    "mrtpu_trainer_lease_generation",
    "fencing token of the lease this process last held")


class TrainerFencedError(LeaseLostError):
    """This trainer's lease is definitively gone (expired and reaped by
    a successor's acquire, or superseded).  Raised at the next step
    boundary; the holder must stop committing state — the successor's
    restored lineage is now authoritative."""


class TrainerLease:
    """Client handle on the singleton trainer-lease document."""

    SINGLETON_ID = "trainer"
    COLL = "trainer_lease"

    def __init__(self, connection: Connection,
                 holder: Optional[str] = None,
                 lease: float = DEFAULT_TRAINER_LEASE) -> None:
        self._cnn = connection
        self.holder = holder or (
            f"trainer-{socket.gethostname()}-{uuid.uuid4().hex[:6]}")
        self.lease = float(lease)
        self.tmpname = uuid.uuid4().hex[:12]
        #: fencing token of OUR current tenure (None = not holding)
        self.generation: Optional[int] = None
        self._seeded = False

    @property
    def ns(self) -> str:
        return self._cnn.ns(self.COLL)

    def _guard(self) -> Dict[str, Any]:
        return {"_id": self.SINGLETON_ID, "holder": self.holder,
                "tmpname": self.tmpname, "generation": self.generation}

    def _seed(self) -> None:
        """Create the singleton iff absent.  The upsert query matches
        only a doc WITHOUT a holder field, and the store's duplicate-_id
        upsert rule refuses to overwrite an existing doc — so two racing
        seeds (or a seed racing an acquire) can never clobber a held
        lease."""
        self._cnn.connect().update(
            self.ns,
            {"_id": self.SINGLETON_ID, "holder": {"$exists": False}},
            {"$set": {"holder": None, "lease_expires": 0.0,
                      "generation": 0}},
            upsert=True)

    def try_acquire(self) -> bool:
        """One atomic claim attempt: succeeds when the lease is free
        (released) or expired (holder presumed dead).  On success this
        handle owns the lease and carries a fresh, strictly increasing
        ``generation``."""
        if not self._seeded:
            # once per handle: a standby polling acquire() for hours
            # must pay ONE board round-trip per poll, not a redundant
            # seed upsert alongside every claim attempt
            self._seed()
            self._seeded = True
        doc = self._cnn.connect().find_and_modify(
            self.ns,
            {"_id": self.SINGLETON_ID,
             "$or": [{"holder": None},
                     {"lease_expires": {"$lt": docstore.now()}}]},
            {"$set": {"holder": self.holder, "tmpname": self.tmpname,
                      "lease_expires": docstore.now() + self.lease},
             "$inc": {"generation": 1}})
        if doc is None:
            _ACQUIRES.inc(outcome="busy")
            return False
        self.generation = int(doc["generation"])
        _ACQUIRES.inc(outcome="acquired")
        _GENERATION.set(self.generation)
        return True

    def acquire(self, timeout: Optional[float] = None,
                poll: float = 0.2) -> int:
        """Block until acquired (a successor waiting out a dead
        holder's lease); returns the generation.  *timeout* None waits
        forever."""
        give_up = (time.monotonic() + timeout) if timeout is not None \
            else None
        while True:
            if self.try_acquire():
                return self.generation
            if give_up is not None and time.monotonic() >= give_up:
                raise TimeoutError(
                    f"trainer lease {self.ns} not acquired within "
                    f"{timeout}s (held by another trainer)")
            time.sleep(poll)

    def heartbeat(self) -> bool:
        """Extend our lease; returns whether we still own it.  False is
        definitive (guarded update matched nothing on a working RPC);
        a transport failure raises and proves NOTHING — callers that
        need certainty use :meth:`ensure_owned`."""
        if self.generation is None:
            return False
        n = self._cnn.connect().update(
            self.ns, self._guard(),
            {"$set": {"lease_expires": docstore.now() + self.lease}})
        _BEATS.inc(outcome="owned" if n else "lost")
        return n > 0

    def ensure_owned(self, max_wait: Optional[float] = None,
                     poll: float = 0.1) -> None:
        """The step-boundary fence gate: returns only with PROOF of
        ownership; raises :class:`TrainerFencedError` on definitive
        loss.  Transport errors mean ownership is UNKNOWN — we retry
        (the partition may heal) up to *max_wait* (default: 4 lease
        periods), after which we fence conservatively: we cannot have
        extended the lease all this time, so a successor is free to
        hold it, and committing blind would race that successor."""
        if max_wait is None:
            max_wait = 4.0 * self.lease
        give_up = time.monotonic() + max_wait
        while True:
            try:
                owned = self.heartbeat()
            except OSError as exc:
                _BEATS.inc(outcome="error")
                if time.monotonic() >= give_up:
                    _FENCES.inc()
                    raise TrainerFencedError(
                        f"trainer lease unverifiable for {max_wait:.1f}s "
                        f"({exc}); fencing conservatively") from exc
                time.sleep(poll)
                continue
            if owned:
                return
            _FENCES.inc()
            raise TrainerFencedError(
                f"trainer lease lost (holder {self.holder}, "
                f"generation {self.generation}): a successor may hold "
                "it — fencing at this step boundary")

    def release(self) -> bool:
        """Clean handoff: clear the holder so a successor's acquire
        succeeds IMMEDIATELY (no expiry wait).  Guarded — releasing a
        lease we no longer hold is a no-op, never a theft."""
        if self.generation is None:
            return False
        n = self._cnn.connect().update(
            self.ns, self._guard(),
            {"$set": {"holder": None, "lease_expires": 0.0}})
        self.generation = None
        return n > 0

    def peek(self) -> Optional[Dict[str, Any]]:
        """The current lease document (observability; statusz reads it)."""
        return self._cnn.connect().find_one(
            self.ns, {"_id": self.SINGLETON_ID})


#: default board-primary lease (seconds) — the failover detection
#: window: a SIGKILLed primary's standby takes over within one of
#: these.  Must be comfortably under the board clients' retry deadline
#: (httpclient.BOARD_DEADLINE, 12s) so a mutation in flight at the kill
#: survives the takeover inside its own budget.
DEFAULT_BOARD_LEASE = 2.0


class BoardLease(TrainerLease):
    """The board-primary election: the same guarded singleton
    (seed-iff-absent, free-or-expired claim, ``$inc`` generation
    fencing token) pointed at the HA directory's own little
    :class:`~.docstore.DirDocStore` — the one store that must NOT live
    on the board it elects.  The generation is stamped into every
    mutation-log entry the holder appends, so a deposed primary's
    straggling appends are identifiable (and skipped) on replay
    (coord/ha.py)."""

    SINGLETON_ID = "board"
    COLL = "board_lease"

    def __init__(self, cnn, holder: Optional[str] = None,
                 lease: float = DEFAULT_BOARD_LEASE) -> None:
        super().__init__(
            cnn,
            holder=holder or (f"board-{socket.gethostname()}-"
                              f"{os.getpid()}-{uuid.uuid4().hex[:6]}"),
            lease=lease)
