"""Connection: docstore handle + errors channel + batched inserts.

Parity with the reference's ``cnn`` class (mapreduce/cnn.lua): connect with
auto-reconnect (cnn.lua:34-39 — moot for our in-proc/dir backends but the
API shape stays), the ``errors`` collection as a remote log channel
(cnn.lua:55-71), and buffered batch inserts flushed at
``MAX_PENDING_INSERTS`` (cnn.lua:73-104, 50k in the reference).
"""

from __future__ import annotations

import socket
import traceback
from typing import Any, Callable, Dict, List, Optional

from ..utils.constants import MAX_PENDING_INSERTS
from . import docstore
from .docstore import DocStore


class Connection:
    """A named database (collection-name prefix) over a :class:`DocStore`.

    Reference: ``cnn(connstr, dbname, auth)`` (cnn.lua:106-113); there
    ``auth`` is a ``{user=..., password=...}`` table re-applied on every
    reconnect (cnn.lua:34-39).  Here it is the shared-secret bearer token
    for the networked backends (docserver/blobserver) — pass a plain
    token string, or a reference-shaped dict whose ``password`` (or
    ``token``) field is used.  Ignored by the in-process/dir backends,
    which have no wire to guard.
    """

    def __init__(self, connstr: str, dbname: str,
                 auth: Optional[Any] = None,
                 retry: Optional[Any] = None) -> None:
        self.connstr = connstr
        self.dbname = dbname
        self.auth = auth
        #: RetryPolicy for the networked planes; threaded through to the
        #: board client (connect) AND to any storage handle opened for a
        #: job of this connection (job.py), so one CLI flag set governs
        #: both sockets.  None = httpclient.DEFAULT_RETRY_POLICY.
        self.retry_policy = retry
        self._store: Optional[DocStore] = None
        # pending batched inserts: coll -> list of (doc, callback)
        self._pending: Dict[str, List[tuple]] = {}

    def auth_token(self) -> Optional[str]:
        """The bearer token in whatever shape it arrived: the ``auth``
        param (str, or a reference-shaped dict), else embedded in the
        connstr (``http://TOKEN@HOST:PORT``) — so a connstr-carried token
        reaches the storage plane too, not just the board socket."""
        from ..utils.httpclient import split_embedded_token

        if isinstance(self.auth, dict):
            return self.auth.get("password") or self.auth.get("token")
        if self.auth:
            return self.auth
        if self.connstr.startswith("http://"):
            # parse per replica endpoint: a token embedded in ANY
            # member of a multi-endpoint (HA) connstr authenticates
            # the whole replica set
            for member in self.connstr[len("http://"):].split(","):
                token = split_embedded_token(member)[0]
                if token:
                    return token
        return None

    def board_hostports(self) -> List[str]:
        """Every ``HOST:PORT`` of an http:// board connstr — one entry
        per replica of a multi-endpoint (HA) board,
        ``http://[TOKEN@]H1:P1,H2:P2``.  The ambient-auth scope must
        cover ALL of them: a client that failed over mid-job still
        speaks to its own cluster."""
        from ..utils.httpclient import split_embedded_token

        if not self.connstr.startswith("http://"):
            return []
        # split members FIRST, token per member second (the auth_token
        # / FailoverClient parse order): a token embedded in a NON-
        # first member must not eat the earlier members' addresses
        return [split_embedded_token(m)[1]
                for m in self.connstr[len("http://"):].split(",") if m]

    def board_hostport(self) -> Optional[str]:
        """The board address for single-handle consumers (the
        telemetry pushers): every replica of a multi-endpoint board,
        comma-joined — the form FailoverClient/acquire_pusher accept —
        so a pusher follows the primary across a failover."""
        hps = self.board_hostports()
        return ",".join(hps) if hps else None

    # -- connection -----------------------------------------------------

    def connect(self) -> DocStore:
        """Reference: cnn.lua:34-39 (cached connection, auth on connect)."""
        if self._store is None:
            self._store = docstore.connect(self.connstr,
                                           auth=self.auth_token(),
                                           retry=self.retry_policy)
        return self._store

    def ns(self, coll: str) -> str:
        """Namespace a collection under this db (Mongo's ``db.coll``)."""
        return f"{self.dbname}.{coll}"

    # -- errors channel ---------------------------------------------------
    # Reference: cnn.lua:55-71; workers insert, the server drains and
    # prints mid-poll (server.lua:219-228).

    def insert_error(self, worker_name: str, msg: str) -> None:
        self.connect().insert(self.ns("errors"),
                              {"worker": worker_name, "msg": msg,
                               "time": docstore.now()})

    def insert_exception(self, worker_name: str, exc: BaseException) -> None:
        msg = "".join(traceback.format_exception(
            type(exc), exc, exc.__traceback__))
        self.insert_error(worker_name, msg)

    def get_errors(self) -> List[Dict[str, Any]]:
        return self.connect().find(self.ns("errors"))

    def remove_errors(self, ids: List[str]) -> None:
        if ids:
            self.connect().remove(self.ns("errors"), {"_id": {"$in": ids}})

    # -- batched inserts --------------------------------------------------
    # Reference: cnn.lua:73-104 `annotate_insert`/`flush_pending_inserts`;
    # the server uses it to bulk-create 50k job docs at a time
    # (server.lua:316-325).

    def annotate_insert(self, coll: str, doc: Dict[str, Any],
                        callback: Optional[Callable] = None) -> None:
        self._pending.setdefault(coll, []).append((doc, callback))
        total = sum(len(v) for v in self._pending.values())
        if total >= MAX_PENDING_INSERTS:
            self.flush_pending_inserts(0)

    def flush_pending_inserts(self, min_pending: int = 0) -> None:
        total = sum(len(v) for v in self._pending.values())
        if total <= min_pending:
            return
        store = self.connect()
        for coll, entries in self._pending.items():
            if not entries:
                continue
            store.insert_many(coll, [doc for doc, _ in entries])
            for _, cb in entries:
                if cb is not None:
                    cb()
        self._pending.clear()

    # -- misc -------------------------------------------------------------

    @staticmethod
    def hostname() -> str:
        """Reference: utils.get_hostname via ``hostname`` (utils.lua:72)."""
        return socket.gethostname()
