"""Durable shared state: the persistent-table singleton AND the board
mutation log.

Parity with mapreduce/persistent_table.lua: a named singleton doc usable as
shared runtime config across processes — ``set``/``update`` with a
timestamp-guarded optimistic write (persistent_table.lua:41-74), spin
``lock``/``unlock`` built on find-and-modify (persistent_table.lua:113-161),
``read_only`` mode, ``drop``.  The APRIL-ANN training harness stores its
experiment config in one of these (examples/APRIL-ANN/common.lua:227).

Differences from the reference (intentional): attribute-style access is via
``[]``/``get`` rather than metatable magic; the dirty/commit split is
explicit (``set`` stages locally, ``update`` syncs) exactly like the
reference's semantics.

:class:`MutationLog` is the durability layer UNDER the board itself
(coord/ha.py): where the reference delegates control-plane durability to
mongod's disk, the rebuild's docserver appends every board mutation to
one shared append-only JSONL file that a standby replica tails and a
restarted process replays — the write-ahead log the HA story and the
durable single-node board both ride.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

from .connection import Connection
from . import docstore


class BoardLogCorruptError(RuntimeError):
    """A COMPLETE line of the board mutation log failed to parse: the
    log is damaged and a replica must refuse to serve from it rather
    than silently skip a mutation and diverge.  (A torn FINAL line
    without its newline is NOT corruption — it is an append the writer
    died inside, whose client never got a response; the reader simply
    stops before it.)"""


class MutationLog:
    """Append-only JSONL mutation log on a shared directory.

    * ``append(entry)`` — one ``os.write`` of one ``\\n``-terminated
      line on an ``O_APPEND`` fd: atomic interleaving between the
      primary and a (fenced, racing) stale writer, immediately visible
      to tailing readers, and durable across SIGKILL of the writer (the
      bytes are the kernel's once write() returns).  ``fsync=True``
      additionally survives host/power death at a per-append cost.
    * ``read_from(offset)`` — parse complete lines from *offset*; the
      tail primitive.  Returns ``(entries, new_offset)``; a trailing
      partial line is left for the next poll.

    Entry ordering IS application ordering: the appender must hold its
    store mutation and the append in one critical section
    (coord/ha.py's ReplicatedDocStore does), so a replay reproduces the
    primary's document state exactly.
    """

    def __init__(self, path: str, fsync: bool = False) -> None:
        self.path = path
        self.fsync = bool(fsync)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._fd = os.open(path, os.O_CREAT | os.O_WRONLY | os.O_APPEND,
                           0o644)
        self._lock = threading.Lock()
        self.appended = 0

    def append(self, entry: Dict[str, Any]) -> None:
        self.append_many([entry])

    def append_many(self, entries: List[Dict[str, Any]]) -> None:
        """Append *entries* as ONE ``os.write`` — the atomic unit the
        HA dedupe contract rides: a request's mutation entries and its
        recorded response either all reach the log or none do."""
        if not entries:
            return
        data = b"".join(
            (json.dumps(e, separators=(",", ":"), sort_keys=True)
             + "\n").encode()
            for e in entries)
        with self._lock:
            # finish a short write (ENOSPC-with-some-room, NFS): a
            # permanently torn line would read as a garbled COMPLETE
            # line once the next append lands, bricking every replica.
            # An os.write that RAISES propagates — the primary answers
            # an error and nothing was acknowledged.
            view = memoryview(data)
            while view:
                n = os.write(self._fd, view)
                view = view[n:]
            if self.fsync:
                os.fsync(self._fd)
            self.appended += len(entries)

    def size(self) -> int:
        try:
            return os.stat(self.path).st_size
        except FileNotFoundError:
            return 0

    def read_from(self, offset: int,
                  ) -> Tuple[List[Dict[str, Any]], int]:
        """Complete entries from byte *offset* on; ``new_offset`` is
        the position just past the last complete line.  A garbled
        COMPLETE line raises :class:`BoardLogCorruptError`."""
        try:
            with open(self.path, "rb") as f:
                f.seek(offset)
                data = f.read()
        except FileNotFoundError:
            return [], offset
        if not data:
            return [], offset
        end = data.rfind(b"\n")
        if end < 0:
            return [], offset  # only a torn tail so far
        out: List[Dict[str, Any]] = []
        pos = offset
        for line in data[:end + 1].splitlines():
            pos += len(line) + 1
            if not line.strip():
                continue
            try:
                doc = json.loads(line)
                if not isinstance(doc, dict):
                    raise ValueError("entry is not an object")
            except (json.JSONDecodeError, UnicodeDecodeError,
                    ValueError) as exc:
                raise BoardLogCorruptError(
                    f"board log {self.path}: complete line at "
                    f"~byte {pos} unparseable ({exc})") from exc
            out.append(doc)
        return out, offset + end + 1

    def replay(self, offset: int = 0) -> Iterator[Dict[str, Any]]:
        entries, _ = self.read_from(offset)
        return iter(entries)

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                try:
                    os.close(self._fd)
                except OSError:
                    pass
                self._fd = None  # type: ignore[assignment]


class PersistentTable:
    """Reference: ``persistent_table(name, {cnn_string, dbname, collection,
    read_only})`` (persistent_table.lua:210-250)."""

    SINGLETON_ID = "unique_key"  # reference pins _id (persistent_table.lua:44)

    def __init__(self, name: str, connection: Connection,
                 collection: str = "persistent_tables",
                 read_only: bool = False) -> None:
        self._name = name
        self._cnn = connection
        self._coll = connection.ns(collection)
        self._read_only = read_only
        self._dirty: Dict[str, Any] = {}
        self._content: Dict[str, Any] = {}
        self.update()

    def _id(self) -> str:
        return f"{self.SINGLETON_ID}.{self._name}"

    # -- sync -------------------------------------------------------------

    def update(self) -> None:
        """Push staged writes (if any) with an optimistic timestamp guard,
        then re-read (persistent_table.lua:41-74)."""
        store = self._cnn.connect()
        if self._dirty and not self._read_only:
            remote = store.find_one(self._coll, {"_id": self._id()})
            base_ts = (remote or {}).get("timestamp", 0)
            fields = {k: v for k, v in self._dirty.items()}
            n = store.update(
                self._coll,
                {"_id": self._id(),
                 "$or": [{"timestamp": base_ts},
                         {"timestamp": {"$exists": False}}]},
                {"$set": fields, "$inc": {"timestamp": 1}},
                upsert=(remote is None),
            )
            if n == 0:
                raise RuntimeError(
                    f"persistent_table {self._name!r}: concurrent update "
                    "conflict (timestamp moved)")
            self._dirty.clear()
        doc = store.find_one(self._coll, {"_id": self._id()})
        self._content = {k: v for k, v in (doc or {}).items()
                         if k not in ("_id", "_lock")}

    def set(self, key: str, value: Any) -> None:
        """Stage a write; visible locally at once, remotely at update()
        (persistent_table.lua:98-111)."""
        if self._read_only:
            raise RuntimeError(f"persistent_table {self._name!r} is read-only")
        self._dirty[key] = value
        self._content[key] = value

    def get(self, key: str, default: Any = None) -> Any:
        return self._content.get(key, default)

    def __getitem__(self, key: str) -> Any:
        return self._content[key]

    def __setitem__(self, key: str, value: Any) -> None:
        self.set(key, value)

    def __contains__(self, key: str) -> bool:
        return key in self._content

    def dirty(self) -> bool:
        return bool(self._dirty)

    # -- distributed lock -------------------------------------------------
    # Reference: spin-lock via findAndModify on a `_lock` field
    # (persistent_table.lua:113-161).

    def lock(self, timeout: float = 30.0, poll: float = 0.01) -> None:
        store = self._cnn.connect()
        deadline = docstore.now() + timeout
        # ensure the doc exists so find_and_modify has something to grab
        store.update(self._coll, {"_id": self._id()},
                     {"$set": {"_lock_init": True}}, upsert=True)
        while True:
            got = store.find_and_modify(
                self._coll,
                {"_id": self._id(),
                 "$or": [{"_lock": False}, {"_lock": {"$exists": False}}]},
                {"$set": {"_lock": True}})
            if got is not None:
                return
            if docstore.now() > deadline:
                raise TimeoutError(
                    f"persistent_table {self._name!r}: lock timeout")
            time.sleep(poll)

    def unlock(self) -> None:
        self._cnn.connect().update(self._coll, {"_id": self._id()},
                                   {"$set": {"_lock": False}})

    def drop(self) -> None:
        """persistent_table.lua drop: delete the doc; local view empties."""
        self._cnn.connect().remove(self._coll, {"_id": self._id()})
        self._content.clear()
        self._dirty.clear()
