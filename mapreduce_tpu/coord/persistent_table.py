"""Distributed singleton key/value document with optimistic concurrency.

Parity with mapreduce/persistent_table.lua: a named singleton doc usable as
shared runtime config across processes — ``set``/``update`` with a
timestamp-guarded optimistic write (persistent_table.lua:41-74), spin
``lock``/``unlock`` built on find-and-modify (persistent_table.lua:113-161),
``read_only`` mode, ``drop``.  The APRIL-ANN training harness stores its
experiment config in one of these (examples/APRIL-ANN/common.lua:227).

Differences from the reference (intentional): attribute-style access is via
``[]``/``get`` rather than metatable magic; the dirty/commit split is
explicit (``set`` stages locally, ``update`` syncs) exactly like the
reference's semantics.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from .connection import Connection
from . import docstore


class PersistentTable:
    """Reference: ``persistent_table(name, {cnn_string, dbname, collection,
    read_only})`` (persistent_table.lua:210-250)."""

    SINGLETON_ID = "unique_key"  # reference pins _id (persistent_table.lua:44)

    def __init__(self, name: str, connection: Connection,
                 collection: str = "persistent_tables",
                 read_only: bool = False) -> None:
        self._name = name
        self._cnn = connection
        self._coll = connection.ns(collection)
        self._read_only = read_only
        self._dirty: Dict[str, Any] = {}
        self._content: Dict[str, Any] = {}
        self.update()

    def _id(self) -> str:
        return f"{self.SINGLETON_ID}.{self._name}"

    # -- sync -------------------------------------------------------------

    def update(self) -> None:
        """Push staged writes (if any) with an optimistic timestamp guard,
        then re-read (persistent_table.lua:41-74)."""
        store = self._cnn.connect()
        if self._dirty and not self._read_only:
            remote = store.find_one(self._coll, {"_id": self._id()})
            base_ts = (remote or {}).get("timestamp", 0)
            fields = {k: v for k, v in self._dirty.items()}
            n = store.update(
                self._coll,
                {"_id": self._id(),
                 "$or": [{"timestamp": base_ts},
                         {"timestamp": {"$exists": False}}]},
                {"$set": fields, "$inc": {"timestamp": 1}},
                upsert=(remote is None),
            )
            if n == 0:
                raise RuntimeError(
                    f"persistent_table {self._name!r}: concurrent update "
                    "conflict (timestamp moved)")
            self._dirty.clear()
        doc = store.find_one(self._coll, {"_id": self._id()})
        self._content = {k: v for k, v in (doc or {}).items()
                         if k not in ("_id", "_lock")}

    def set(self, key: str, value: Any) -> None:
        """Stage a write; visible locally at once, remotely at update()
        (persistent_table.lua:98-111)."""
        if self._read_only:
            raise RuntimeError(f"persistent_table {self._name!r} is read-only")
        self._dirty[key] = value
        self._content[key] = value

    def get(self, key: str, default: Any = None) -> Any:
        return self._content.get(key, default)

    def __getitem__(self, key: str) -> Any:
        return self._content[key]

    def __setitem__(self, key: str, value: Any) -> None:
        self.set(key, value)

    def __contains__(self, key: str) -> bool:
        return key in self._content

    def dirty(self) -> bool:
        return bool(self._dirty)

    # -- distributed lock -------------------------------------------------
    # Reference: spin-lock via findAndModify on a `_lock` field
    # (persistent_table.lua:113-161).

    def lock(self, timeout: float = 30.0, poll: float = 0.01) -> None:
        store = self._cnn.connect()
        deadline = docstore.now() + timeout
        # ensure the doc exists so find_and_modify has something to grab
        store.update(self._coll, {"_id": self._id()},
                     {"$set": {"_lock_init": True}}, upsert=True)
        while True:
            got = store.find_and_modify(
                self._coll,
                {"_id": self._id(),
                 "$or": [{"_lock": False}, {"_lock": {"$exists": False}}]},
                {"$set": {"_lock": True}})
            if got is not None:
                return
            if docstore.now() > deadline:
                raise TimeoutError(
                    f"persistent_table {self._name!r}: lock timeout")
            time.sleep(poll)

    def unlock(self) -> None:
        self._cnn.connect().update(self._coll, {"_id": self._id()},
                                   {"$set": {"_lock": False}})

    def drop(self) -> None:
        """persistent_table.lua drop: delete the doc; local view empties."""
        self._cnn.connect().remove(self._coll, {"_id": self._id()})
        self._content.clear()
        self._dirty.clear()
