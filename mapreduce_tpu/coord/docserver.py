"""Networked control plane: the docstore served over HTTP.

The reference's whole deployment story is "point any worker on any
machine at one connstr" — mongod is reachable over TCP
(/root/reference/mapreduce/cnn.lua:34-39, worker.lua:20-27).  The
rebuild's ``mem://`` and ``dir://`` backends cover one process and one
filesystem; this module covers the network: a :class:`DocServer` owns a
single authoritative :class:`~.docstore.MemoryDocStore` and speaks a tiny
JSON-RPC over HTTP, and :class:`HttpDocStore` is the client-side
:class:`~.docstore.DocStore` behind the ``http://HOST:PORT`` connstr.
Any worker on any machine can now claim jobs with zero shared
filesystem — the same topology as N workers dialing one mongod.

Atomicity: every RPC executes under the backing store's lock on the
server, so ``find_and_modify`` claims and ``$inc`` retries keep exactly
the single-document atomicity the in-process backends give
(task.lua:294-309's racy claim emulation is still genuinely atomic here).

Retry safety: a broken socket mid-request leaves the client unsure
whether the server applied the op.  Mutating RPCs therefore carry a
client-generated request id (``SESSION:SEQ``); the server remembers
recently answered ids and replays the recorded response instead of
re-applying — exactly-once across any number of reconnect retries, so a
retried claim cannot double-claim and a retried ``$inc`` cannot
double-count (the double-apply hazard the blob client tolerates only
because blob PUTs are idempotent whole-content writes, httpstore.py).
The remembered-answer cache is bounded (``_DEDUPE_CAP``); when a retry
straggles in *after* its entry was evicted the server refuses it with
:class:`DedupeEvictedError` rather than silently re-applying — the
monotonic per-session seq is what lets it tell that straggler from a
fresh request.
"""

from __future__ import annotations

import collections
import contextlib
import http.server
import itertools
import json
import os
import threading
import time
import urllib.parse
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..obs import metrics as _metrics
from ..obs.collector import Collector
from ..obs.statusz import cluster_status, update_board_gauges
from ..obs.trace import TRACE_HEADER, TRACER
from ..utils.httpclient import (
    NOT_PRIMARY_STATUS, FailoverClient, NotPrimaryError, RetryPolicy,
    check_auth, default_auth_token)
from .docstore import Doc, DocStore, MemoryDocStore, Query

_REQUESTS = _metrics.counter(
    "mrtpu_docserver_requests_total",
    "docserver RPCs served (labels: op, outcome=ok|error|replayed|"
    "evicted|unauthorized|bad_request)")
_RPC_SECONDS = _metrics.histogram(
    "mrtpu_docserver_rpc_seconds",
    "docserver RPC execution latency (labels: op)")
_DEDUPE_HITS = _metrics.counter(
    "mrtpu_docserver_dedupe_hits_total",
    "mutating RPC retries answered from the dedupe cache")
_DEDUPE_EVICTED = _metrics.counter(
    "mrtpu_docserver_dedupe_evicted_total",
    "straggler retries refused because their dedupe entry was evicted")
_SCRAPES = _metrics.counter(
    "mrtpu_docserver_scrapes_total",
    "GET requests to the exposition endpoints (labels: path)")

# ops whose second application would change state: answered once, replayed
# from the dedupe cache on retry.  Reads re-execute harmlessly.
_MUTATING_OPS = frozenset(
    {"insert", "insert_many", "update", "find_and_modify",
     "find_and_modify_many", "remove", "drop_collection"})

_DEDUPE_CAP = 4096   # answered-request ids remembered per server
_SESSION_CAP = 1024  # per-client eviction watermarks remembered


class DedupeEvictedError(IOError):
    """A mutating RPC's retry arrived after its dedupe entry was evicted:
    the server can no longer tell whether the original applied, so it
    refuses to re-apply and the client must surface the ambiguity instead
    of silently double-claiming / double-counting."""


def _rid_session_seq(rid: str) -> Tuple[Optional[str], Optional[int]]:
    """Split a ``SESSION:SEQ`` rid; (None, None) for legacy opaque rids
    (no eviction detection possible for those, matching old behavior)."""
    session, sep, seq = rid.rpartition(":")
    if not sep:
        return None, None
    try:
        return session, int(seq)
    except ValueError:
        return None, None


class _RpcHandler(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    # status line / headers and body leave in separate writes on an
    # unbuffered socket; with Nagle on, the body write stalls ~40ms per
    # keep-alive request waiting for the client's delayed ACK
    disable_nagle_algorithm = True
    store: DocStore            # set by DocServer
    done: "collections.OrderedDict[str, bytes]"   # rid -> recorded response
    inflight: Dict[str, threading.Event]          # rid -> original executing
    evicted: "collections.OrderedDict[str, int]"  # session -> max evicted seq
    dedupe_lock: threading.Lock
    auth_token: Optional[str]  # None = open server
    collector: Collector       # cluster telemetry sink (obs/collector)
    scheduler: Any             # sched.Scheduler hosted on self.store
    ha: Any = None             # coord/ha.HaController when HA-deployed

    def log_message(self, *a):  # quiet
        pass

    def _not_primary(self, length: int) -> None:
        """Answer a request that needs the primary from a replica that
        is not (standby, fenced, or mid-takeover): HTTP 421, which is
        NOT in the clients' retryable-status set — a FailoverClient
        rotates to the next endpoint immediately instead of burning
        its budget here."""
        self.rfile.read(length)
        _REQUESTS.inc(op="-", outcome="not_primary")
        self._respond(NOT_PRIMARY_STATUS, json.dumps(
            {"ok": False, "type": "NotPrimaryError",
             "error": f"this board replica is {self.ha.role}; dial "
                      "the lease-holding primary"}).encode())

    def _respond(self, code: int, body: bytes,
                 ctype: str = "application/json") -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self) -> None:
        if self.ha is not None and not self.ha.is_primary():
            # every POST surface mutates or feeds primary-local state;
            # a replica serves none of them
            return self._not_primary(
                int(self.headers.get("Content-Length", 0)))
        if self.path == "/telemetry":
            return self._do_telemetry()
        if self.path == "/tasks":
            return self._do_tasks()
        if self.path == "/alertz":
            return self._do_alertz()
        if self.path != "/rpc":
            return self._respond(404, b"{}")
        length = int(self.headers.get("Content-Length", 0))
        if not check_auth(self.auth_token, self.headers):
            # drain the body first so the keep-alive stream stays in sync
            self.rfile.read(length)
            _REQUESTS.inc(op="-", outcome="unauthorized")
            return self._respond(401, json.dumps(
                {"ok": False, "type": "PermissionError",
                 "error": "auth required (bad or missing bearer token)"}
            ).encode())
        try:
            req = json.loads(self.rfile.read(length))
            op = req["op"]
        except (json.JSONDecodeError, KeyError, UnicodeDecodeError,
                TypeError):  # TypeError: valid JSON that isn't an object
            _REQUESTS.inc(op="-", outcome="bad_request")
            return self._respond(400, b"{}")

        rid = req.get("rid") if op in _MUTATING_OPS else None
        if rid is not None:
            answered = self._claim_rid(rid, op)
            if answered is not None:
                return self._respond(200, answered)

        body = None
        t_exec = time.monotonic()
        # on a replicated (HA) board, a rid-carrying request runs as a
        # deferred-log transaction: its mutation entries and recorded
        # response reach the shared mutation log in ONE atomic append,
        # so a standby either replays mutation+answer together or sees
        # neither — the dedupe table survives failover with the state
        defer = (getattr(self.store, "deferred_rid", None)
                 if rid is not None else None)
        txn_ctx = (defer(rid) if defer is not None
                   else contextlib.nullcontext(None))
        not_primary = False
        try:
            with txn_ctx as txn:
                try:
                    # adopt the caller's span (TRACE_HEADER) so this
                    # RPC's span nests under the client-side job/claim
                    # trace in Perfetto
                    with TRACER.adopt(self.headers.get(TRACE_HEADER)), \
                            TRACER.span(f"rpc:{op}",
                                        coll=req.get("coll")):
                        result = self._execute(op, req)
                    body = json.dumps({"ok": True,
                                       "result": result}).encode()
                    _REQUESTS.inc(op=op, outcome="ok")
                except NotPrimaryError:
                    # the self-fence lapsed BETWEEN the do_POST door
                    # check and the write path: answer 421 so the
                    # multi-endpoint client rotates to the standby,
                    # and record NOTHING for the rid — no mutation
                    # applied (the fence precedes the apply), so the
                    # failed-over re-send must execute fresh
                    not_primary = True
                    _REQUESTS.inc(op=op, outcome="not_primary")
                except Exception as exc:
                    # catch EVERYTHING else: a reserved rid must always
                    # get a recorded response, or the client's
                    # reconnect-retry would re-execute a mutation whose
                    # first attempt partially applied (e.g. ENOSPC mid-
                    # multi-update on a dir:// board)
                    body = json.dumps(
                        {"ok": False, "type": type(exc).__name__,
                         "error": str(exc)}).encode()
                    _REQUESTS.inc(op=op, outcome="error")
                if txn is not None:
                    txn.body = body
        finally:
            _RPC_SECONDS.observe(time.monotonic() - t_exec, op=op)
            if rid is not None:
                # body None (not-primary) leaves the rid unrecorded:
                # waiters wake, the re-send executes on the successor
                self._record_rid(rid, body)
        if not_primary:
            return self._respond(NOT_PRIMARY_STATUS, json.dumps(
                {"ok": False, "type": "NotPrimaryError",
                 "error": "primacy lapsed mid-request; rotate"}
            ).encode())
        self._respond(200, body)

    # -- rid dedupe (shared by /rpc and /tasks mutations) -------------------

    def _claim_rid(self, rid: str, op: str) -> Optional[bytes]:
        """Reserve *rid* for execution, or return the bytes to answer a
        duplicate with.  None means the caller executes and MUST call
        :meth:`_record_rid` (its finally block) so waiters resolve.

        A retry can arrive while the original is STILL executing (the
        client only retries after its socket broke, but the server
        thread serving the broken socket may not have finished):
        reserving the rid before executing makes the duplicate wait for
        the recorded response instead of re-applying."""
        with self.dedupe_lock:
            replay = self.done.get(rid)
            waiter = None if replay is not None else self.inflight.get(rid)
            stale = False
            if replay is None and waiter is None:
                session, seq = _rid_session_seq(rid)
                if (session is not None and seq is not None
                        and seq <= self.evicted.get(session, -1)):
                    # straggling retry of an EVICTED entry: the answer
                    # is gone, so whether the original applied is
                    # unknowable — refuse loudly, never re-apply
                    stale = True
                else:
                    self.inflight[rid] = threading.Event()
        if stale:
            _DEDUPE_EVICTED.inc()
            _REQUESTS.inc(op=op, outcome="evicted")
            return json.dumps(
                {"ok": False, "type": "DedupeEvictedError",
                 "error": f"rid {rid}: retry arrived after its dedupe "
                          "entry was evicted; cannot guarantee "
                          "exactly-once"}).encode()
        if replay is not None:
            _DEDUPE_HITS.inc()
            _REQUESTS.inc(op=op, outcome="replayed")
            return replay
        if waiter is not None:
            waiter.wait(timeout=60)
            with self.dedupe_lock:
                replay = self.done.get(rid)
            if replay is None:  # original died without recording
                replay = json.dumps(
                    {"ok": False, "type": "IOError",
                     "error": "retried rpc: original did not complete"}
                ).encode()
                # NOT a dedupe hit: the cache had no answer — a
                # wedged original must show as an error, not a replay
                _REQUESTS.inc(op=op, outcome="error")
            else:
                _DEDUPE_HITS.inc()
                _REQUESTS.inc(op=op, outcome="replayed")
            return replay
        return None

    def _record_rid(self, rid: str, body: Optional[bytes]) -> None:
        with self.dedupe_lock:
            ev = self.inflight.pop(rid, None)
            if body is not None:  # BaseException: leave unrecorded
                self._remember_locked(rid, body)
        if ev is not None:
            ev.set()

    @classmethod
    def _remember_locked(cls, rid: str, body: bytes) -> None:
        """Insert one answered rid into the dedupe cache (dedupe_lock
        HELD), evicting the oldest past the cap into the per-session
        high-water marks — seqs are monotonic per session, so max ==
        newest evicted."""
        cls.done[rid] = body
        while len(cls.done) > _DEDUPE_CAP:
            old_rid, _ = cls.done.popitem(last=False)
            s, q = _rid_session_seq(old_rid)
            if s is not None and q is not None:
                cls.evicted[s] = max(q, cls.evicted.get(s, -1))
                cls.evicted.move_to_end(s)
                while len(cls.evicted) > _SESSION_CAP:
                    cls.evicted.popitem(last=False)

    # -- the HA replayer's dedupe feed (coord/ha.py, duck-typed) -----------

    @classmethod
    def remember_answer(cls, rid: str, body: bytes) -> None:
        """Seed a REPLAYED rid->response pair (a mutation the old
        primary answered): a client retry that failed over here
        replays the recorded answer instead of re-applying."""
        with cls.dedupe_lock:
            cls._remember_locked(rid, body)

    @classmethod
    def refuse_rid(cls, rid: str) -> None:
        """Mark a rid whose mutations were logged WITHOUT a recorded
        response (the old primary died mid-request): its retry must be
        refused with the loud dedupe ambiguity, never re-applied.
        Rides the eviction watermark — the client allocates seqs
        monotonically and serializes mutations per handle, so the
        watermark refuses exactly this rid."""
        s, q = _rid_session_seq(rid)
        if s is None or q is None:
            return
        with cls.dedupe_lock:
            cls.evicted[s] = max(q, cls.evicted.get(s, -1))
            cls.evicted.move_to_end(s)
            while len(cls.evicted) > _SESSION_CAP:
                cls.evicted.popitem(last=False)

    # -- /tasks: the scheduler surface --------------------------------------

    #: /tasks ops whose second application would change state (deduped);
    #: "tick" is idempotent admission work and re-executes harmlessly
    _TASKS_MUTATING = frozenset({"submit", "cancel"})
    #: serializes ALL /tasks scheduler calls on an HA board: a deferred
    #: submit/cancel holds the store lock for its whole transaction
    #: (wrapper -> scheduler lock order) while a concurrent tick takes
    #: scheduler -> wrapper — this outer lock keeps the two orders from
    #: ever interleaving (set per-server in DocServer.__init__)
    tasks_lock: threading.Lock

    def _do_alertz(self) -> None:
        """Operator mutations on the alerting plane: ``silence`` and
        ``ack``.  Both are durable appends to the generation-fenced
        alert log, so they run primary-only (the do_POST door already
        answered 421 for a standby) and auth-gated like /rpc."""
        length = int(self.headers.get("Content-Length", 0))
        if not check_auth(self.auth_token, self.headers):
            self.rfile.read(length)
            _REQUESTS.inc(op="alertz:-", outcome="unauthorized")
            return self._respond(401, b"{}")
        from ..obs import alerts as _alerts

        if not _alerts.PLANE.configured():
            self.rfile.read(length)
            return self._respond(404, json.dumps(
                {"ok": False, "type": "ValueError",
                 "error": "no alert rules configured (start the "
                 "docserver with --alert or --alert-rules)"}).encode())
        try:
            req = json.loads(self.rfile.read(length))
            op = req["op"]
            if op not in ("silence", "ack"):
                raise KeyError(op)
        except (json.JSONDecodeError, KeyError, UnicodeDecodeError,
                TypeError):
            _REQUESTS.inc(op="alertz:-", outcome="bad_request")
            return self._respond(400, b"{}")
        try:
            if op == "silence":
                result = _alerts.PLANE.silence(
                    str(req["rule"]),
                    float(req.get("duration", 3600.0)))
            else:
                result = _alerts.PLANE.ack(str(req["rule"]))
        except (ValueError, KeyError, TypeError, OSError) as exc:
            _REQUESTS.inc(op=f"alertz:{op}", outcome="error")
            return self._respond(400, json.dumps(
                {"ok": False, "type": type(exc).__name__,
                 "error": str(exc)}).encode())
        _REQUESTS.inc(op=f"alertz:{op}", outcome="ok")
        self._respond(200, json.dumps(
            {"ok": True, "result": result}).encode())

    def _do_tasks(self) -> None:
        """The multi-tenant scheduler surface (sched/scheduler.py):
        ``submit`` / ``cancel`` (rid-deduped like every board mutation
        — a retried submit cannot enqueue a task twice) and ``tick``
        (idempotent admission).  Auth-gated like /rpc."""
        length = int(self.headers.get("Content-Length", 0))
        if not check_auth(self.auth_token, self.headers):
            self.rfile.read(length)
            _REQUESTS.inc(op="tasks:-", outcome="unauthorized")
            return self._respond(401, b"{}")
        try:
            req = json.loads(self.rfile.read(length))
            op = req["op"]
            if op not in ("submit", "cancel", "tick"):
                raise KeyError(op)
        except (json.JSONDecodeError, KeyError, UnicodeDecodeError,
                TypeError):
            _REQUESTS.inc(op="tasks:-", outcome="bad_request")
            return self._respond(400, b"{}")
        rid = req.get("rid") if op in self._TASKS_MUTATING else None
        if rid is not None:
            answered = self._claim_rid(rid, f"tasks:{op}")
            if answered is not None:
                return self._respond(200, answered)
        body = None
        code = 200
        t_exec = time.monotonic()
        # HA boards: a submit/cancel is a multi-mutation transaction —
        # defer its log entries so they commit atomically WITH the
        # recorded response (the do_POST /rpc pattern)
        defer = (getattr(self.store, "deferred_rid", None)
                 if rid is not None else None)
        txn_ctx = (defer(rid) if defer is not None
                   else contextlib.nullcontext(None))
        not_primary = False
        # the tasks_lock guards a lock-order inversion that only exists
        # on an HA board (a deferred submit holds the store lock for
        # its whole transaction while a tick takes scheduler->store);
        # a plain board keeps its concurrent submit/cancel/tick
        lock_ctx = (self.tasks_lock if self.ha is not None
                    else contextlib.nullcontext())
        try:
            with lock_ctx, txn_ctx as txn:
                try:
                    if op == "submit":
                        result = self.scheduler.submit(
                            req["tenant"], db=req.get("db"),
                            params=req.get("params"),
                            priority=int(req.get("priority") or 0),
                            weight=float(req.get("weight") or 1.0),
                            est_jobs=int(req.get("est_jobs") or 0),
                            est_bytes=int(req.get("est_bytes") or 0),
                            kind=req.get("kind") or "server")
                    elif op == "cancel":
                        result = self.scheduler.cancel(
                            req["task_id"],
                            reason=req.get("reason") or "cancelled")
                    else:
                        result = self.scheduler.tick()
                    body = json.dumps({"ok": True,
                                       "result": result}).encode()
                    _REQUESTS.inc(op=f"tasks:{op}", outcome="ok")
                except NotPrimaryError:
                    # primacy lapsed mid-transaction: 421 (the client
                    # rotates), rid left unrecorded — any entries the
                    # transaction already applied commit WITHOUT a
                    # response, so the successor refuses the re-send
                    # loudly instead of double-applying
                    not_primary = True
                    _REQUESTS.inc(op=f"tasks:{op}",
                                  outcome="not_primary")
                except Exception as exc:
                    # same contract as /rpc: a reserved rid always gets
                    # a recorded response, and admission rejections
                    # travel as typed errors (QuotaExceededError
                    # carries its reason) — over the wire as HTTP 429,
                    # which the SchedulerClient deliberately does NOT
                    # retry: backpressure must reject loudly, not turn
                    # into a silent retry storm
                    doc = {"ok": False, "type": type(exc).__name__,
                           "error": str(exc)}
                    reason = getattr(exc, "reason", None)
                    if reason is not None:
                        doc["reason"] = reason
                    body = json.dumps(doc).encode()
                    if type(exc).__name__ == "QuotaExceededError":
                        code = 429
                        _REQUESTS.inc(op=f"tasks:{op}",
                                      outcome="rejected")
                    else:
                        _REQUESTS.inc(op=f"tasks:{op}", outcome="error")
                if txn is not None:
                    txn.body = body
        finally:
            _RPC_SECONDS.observe(time.monotonic() - t_exec,
                                 op=f"tasks:{op}")
            if rid is not None:
                self._record_rid(rid, body)
        if not_primary:
            return self._respond(NOT_PRIMARY_STATUS, json.dumps(
                {"ok": False, "type": "NotPrimaryError",
                 "error": "primacy lapsed mid-request; rotate"}
            ).encode())
        self._respond(code, body)

    def _do_telemetry(self) -> None:
        """The collector's push sink: workers/servers POST span batches +
        metric snapshots here (obs/collector.TelemetryPusher).  Auth-
        gated like /rpc; ingestion failures answer 4xx/5xx and never
        kill the handler thread — a worker whose push bounces just
        counts the loss and keeps working."""
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if not check_auth(self.auth_token, self.headers):
            return self._respond(401, b"{}")
        try:
            payload = json.loads(body)
            if not isinstance(payload, dict):
                raise ValueError("telemetry payload is not an object")
        except (json.JSONDecodeError, UnicodeDecodeError, ValueError):
            return self._respond(400, b"{}")
        try:
            ack = self.collector.push(payload, nbytes=len(body))
        except Exception as exc:
            return self._respond(500, json.dumps(
                {"error": f"{type(exc).__name__}: {exc}"}).encode())
        self._respond(200, json.dumps({"ok": True, **ack}).encode())

    def do_GET(self) -> None:
        """Exposition plane: ``/metrics`` (Prometheus text over the
        process-global registry, with job-board depth gauges refreshed at
        scrape time), ``/statusz`` (JSON cluster snapshot, including the
        collector's per-task roll-ups), ``/tracez`` (this process's span
        ring as Chrome trace JSON — the ``profile`` CLI's bundle feed),
        ``/clusterz`` (the MERGED cluster timeline: every pushed
        process's spans clock-aligned with this process's, one
        Perfetto-loadable file — the ``timeline``/``diagnose`` CLI
        feed), ``/healthz``.  Everything but /healthz is auth-gated like
        the RPC plane (the board's contents leak through all of them);
        /healthz is open — it returns a static liveness body and nothing
        else, and orchestrator probes (k8s httpGet, load balancers)
        cannot send a bearer token."""
        # /queryz carries its parameters in the query string; every
        # other endpoint ignores one (exact-path matching on the split)
        path, _, query = self.path.partition("?")
        if path not in ("/metrics", "/statusz", "/tracez", "/clusterz",
                        "/healthz", "/tasks", "/queryz", "/alertz"):
            return self._respond(404, b"{}")
        if path == "/healthz":
            _SCRAPES.inc(path=path)
            if self.ha is not None:
                # liveness plus ROLE: orchestrator probes and the chaos
                # suite can tell the primary from a standby without auth
                return self._respond(200, json.dumps(
                    {"ok": True, "role": self.ha.role,
                     "primary": self.ha.is_primary()}).encode())
            return self._respond(200, b'{"ok": true}')
        if not check_auth(self.auth_token, self.headers):
            return self._respond(401, b"{}")
        _SCRAPES.inc(path=path)
        try:
            if path == "/metrics":
                update_board_gauges(self.store)
                # SLO gauges (percentile/burn/threshold) are published
                # by evaluation ticks; run one at scrape time so the
                # exposition is current (the board-gauge pattern) and
                # the burn windows sample at scrape cadence
                from ..obs import slo as _slo

                _slo.evaluate(collector=self.collector)
                body = _metrics.REGISTRY.render().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path == "/tracez":
                body = json.dumps(TRACER.chrome_trace()).encode()
                ctype = "application/json"
            elif path == "/queryz":
                # range queries over the durable history plane — served
                # by standbys too (history lives on the shared dir and
                # do_GET has no primary check by design), which is what
                # makes the series survive a board failover
                history = getattr(self.collector, "history", None)
                if history is None:
                    return self._respond(404, json.dumps(
                        {"error": "history not configured (start the "
                         "docserver with --history-dir or --ha-dir)"}
                    ).encode())
                try:
                    doc = self._queryz(history, query)
                except ValueError as exc:
                    # typed 400 body (the /rpc error-envelope shape):
                    # bad step/range/op parameters are the CALLER's
                    # bug, distinguishable from a 500 by machine
                    return self._respond(400, json.dumps(
                        {"ok": False, "type": "ValueError",
                         "error": str(exc)}).encode())
                body = json.dumps(doc, default=float).encode()
                ctype = "application/json"
            elif path == "/alertz":
                # alert lifecycle state — served from standbys too
                # (the plane tails the shared alert log on refresh),
                # so `cli alerts` works against whichever replica
                # answers after a failover
                from ..obs import alerts as _alerts

                if not _alerts.PLANE.configured():
                    return self._respond(404, json.dumps(
                        {"ok": False, "type": "ValueError",
                         "error": "no alert rules configured (start "
                         "the docserver with --alert or "
                         "--alert-rules)"}).encode())
                body = json.dumps(_alerts.alertz_doc(),
                                  default=float).encode()
                ctype = "application/json"
            elif path == "/clusterz":
                # evaluate HERE too: `cli diagnose` may be the first
                # scrape a board ever serves, and _slo_findings reads
                # the derived percentile/burn/threshold gauges this
                # tick publishes — without it a breach the runner's
                # pushed histograms prove would go unnamed
                from ..obs import slo as _slo

                _slo.evaluate(collector=self.collector)
                body = json.dumps(self.collector.cluster_doc(),
                                  default=float).encode()
                ctype = "application/json"
            elif path == "/tasks":
                body = json.dumps(
                    {"tasks": self.scheduler.list_tasks(),
                     "sched": self.scheduler.snapshot()},
                    default=float).encode()
                ctype = "application/json"
            else:
                snap = cluster_status(
                    self.store, collector=self.collector,
                    scheduler=self.scheduler)
                if self.ha is not None:
                    snap["ha"] = self.ha.snapshot()
                body = json.dumps(snap).encode()
                ctype = "application/json"
        except Exception as exc:
            # a scrape must never kill the handler thread mid-chaos; the
            # scraper sees the failure as a 500, not a hung socket
            return self._respond(500, json.dumps(
                {"error": f"{type(exc).__name__}: {exc}"}).encode())
        self._respond(200, body, ctype=ctype)

    @staticmethod
    def _queryz(history: Any, query: str) -> Dict[str, Any]:
        """Parse one /queryz query string and run it.

        ``op=query`` (default): ``metric=FAMILY`` plus repeated
        ``match=label=value`` matchers, ``start``/``end`` (wall
        seconds; <= 0 means relative to now), ``step`` and
        ``fn=raw|rate|increase|delta`` (``by_proc=1`` splits counters
        per pushing proc).  ``op=top``: top-K counter series by rate
        over ``window``.  ``op=trends``: the persisted trend summary
        diagnose consumes.  Raises ValueError on bad parameters (the
        caller answers 400)."""
        params = urllib.parse.parse_qs(query, keep_blank_values=True)

        def one(name: str, default: Optional[str] = None,
                ) -> Optional[str]:
            vals = params.get(name)
            return vals[-1] if vals else default

        op = one("op", "query")
        if op == "top":
            window = float(one("window", "300") or 300)
            return {"op": "top", "window_s": window,
                    "series": history.top_series(
                        k=int(one("k", "10") or 10), window_s=window)}
        if op == "trends":
            return {"op": "trends",
                    "trends": history.trends(
                        window_s=float(one("window", "300") or 300))}
        if op != "query":
            raise ValueError(f"unknown queryz op {op!r}")
        metric = one("metric")
        if not metric:
            raise ValueError("queryz needs metric=FAMILY")
        matchers: Dict[str, str] = {}
        for m in params.get("match", []):
            k, sep, v = m.partition("=")
            if not sep or not k:
                raise ValueError(f"bad matcher {m!r} (want label=value)")
            matchers[k] = v
        start = one("start")
        end = one("end")
        step = one("step")
        return history.query(
            metric, matchers=matchers or None,
            start=float(start) if start is not None else None,
            end=float(end) if end is not None else None,
            step=float(step) if step is not None else None,
            fn=one("fn", "raw") or "raw",
            by_proc=(one("by_proc", "0") or "0").lower()
            in ("1", "true", "yes"))

    def _execute(self, op: str, req: Dict[str, Any]) -> Any:
        store = self.store
        coll = req.get("coll")
        if op == "insert":
            return store.insert(coll, req["doc"])
        if op == "insert_many":
            return store.insert_many(coll, req["docs"])
        if op == "find":
            return store.find(coll, req.get("query"))
        if op == "count":
            return store.count(coll, req.get("query"))
        if op == "update":
            return store.update(coll, req["query"], req["update"],
                                multi=bool(req.get("multi")),
                                upsert=bool(req.get("upsert")))
        if op == "find_and_modify":
            return store.find_and_modify(coll, req["query"], req["update"])
        if op == "find_and_modify_many":
            # the batched claim: one rid-deduped round trip claims up to
            # `limit` jobs (Task.take_next_jobs)
            return store.find_and_modify_many(coll, req["query"],
                                              req["update"],
                                              int(req.get("limit", 1)))
        if op == "remove":
            return store.remove(coll, req.get("query"))
        if op == "drop_collection":
            store.drop_collection(coll)
            return None
        if op == "collections":
            return store.collections()
        if op == "ping":
            return "pong"
        raise ValueError(f"unknown rpc op {op!r}")


class DocServer:
    """Serve a DocStore over HTTP (threaded, stdlib) — the mongod role.

    Wraps a :class:`MemoryDocStore` by default (authoritative state lives
    in this process; its RLock makes each RPC atomic); pass a
    ``DirDocStore`` to make the board durable across server restarts the
    way mongod's disk was.
    """

    def __init__(self, store: Optional[DocStore] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 auth_token: Optional[str] = None,
                 scheduler_config=None,
                 ha_dir: Optional[str] = None,
                 ha_lease: Optional[float] = None,
                 ha_fsync: bool = False,
                 history_dir: Optional[str] = None,
                 history_keep: Optional[int] = None,
                 history_segment_bytes: Optional[int] = None,
                 history_max_age_s: Optional[float] = None,
                 alert_rules: Optional[List[str]] = None,
                 alert_rules_file: Optional[str] = None,
                 alert_webhooks: Optional[List[str]] = None,
                 alert_execs: Optional[List[str]] = None,
                 alert_interval: float = 5.0,
                 alert_damp: Optional[float] = None) -> None:
        # late import: sched builds on coord (no cycle at module load)
        from ..sched.scheduler import Scheduler, SchedulerConfig

        self.ha = None
        if ha_dir is not None:
            if store is not None:
                raise ValueError(
                    "ha_dir and an explicit store are mutually "
                    "exclusive: the HA board's authoritative state is "
                    "the mutation log under ha_dir")
            from .ha import DEFAULT_BOARD_LEASE, HaController

            self.ha = HaController(
                ha_dir,
                lease=(ha_lease if ha_lease is not None
                       else DEFAULT_BOARD_LEASE),
                fsync=ha_fsync)
            bound_store: DocStore = self.ha.store
        else:
            bound_store = store if store is not None else MemoryDocStore()
        # durable telemetry history: defaults onto the HA dir so the
        # standby tails the same segments and keeps serving /queryz
        # after failover; an explicit --history-dir works standalone
        if history_dir is None and ha_dir is not None:
            history_dir = os.path.join(ha_dir, "history")
        self.history = None
        if history_dir is not None:
            from ..obs.history import MetricHistory

            kwargs: Dict[str, Any] = {"fsync": ha_fsync}
            if history_keep is not None:
                kwargs["keep_segments"] = history_keep
            if history_segment_bytes is not None:
                kwargs["max_segment_bytes"] = history_segment_bytes
            if history_max_age_s is not None:
                kwargs["max_segment_age_s"] = history_max_age_s
            self.history = MetricHistory(history_dir, **kwargs)
            # a corrupt segment REFUSES to load (HistoryCorruptError
            # propagates) — better no history plane than a wrong one
            self.history.load()
            # restart-proof burn windows: rebuild the SLO plane's
            # in-memory deques from persisted bucket deltas so a
            # burn-rate alert survives the process that raised it
            from ..obs import slo as _slo

            _slo.PLANE.seed_from_history(self.history)
            # control-ledger outcomes read their before/after evidence
            # from history windows instead of racy in-memory snapshots
            from ..obs import control as _control

            _control.LEDGER.bind_history(self.history)
        # the alerting plane: rules evaluated on this board, every
        # transition appended to a generation-fenced log on the shared
        # dir so a promoted standby resumes pending timers and never
        # re-fires what the dead primary already fired
        self._alert_stop: Optional[threading.Event] = None
        self._alert_thread: Optional[threading.Thread] = None
        self._alert_interval = float(alert_interval)
        self.alerts = None
        rule_specs = list(alert_rules or [])
        if rule_specs or alert_rules_file:
            from ..obs import alerts as _alerts
            from ..obs import slo as _slo

            objective_names = [o.name for o in _slo.PLANE.objectives]
            rules = [_alerts.parse_alert(s, objectives=objective_names)
                     for s in rule_specs]
            if alert_rules_file:
                rules += _alerts.load_rules_file(
                    alert_rules_file, objectives=objective_names)
            sinks: List[Any] = [_alerts.parse_webhook_spec(s)
                                for s in (alert_webhooks or [])]
            sinks += [_alerts.parse_exec_spec(s)
                      for s in (alert_execs or [])]
            if ha_dir is not None:
                alert_dir: Optional[str] = os.path.join(ha_dir, "alerts")
            elif history_dir is not None:
                alert_dir = os.path.join(history_dir, "alerts")
            else:
                alert_dir = None  # burn-only rules, non-durable
            _alerts.PLANE.configure(
                rules, log_dir=alert_dir, fsync=ha_fsync,
                gen_fn=(self.ha.generation if self.ha is not None
                        else None),
                sinks=sinks, flap_damp_s=alert_damp)
            self.alerts = _alerts.PLANE
        handler = type("BoundRpcHandler", (_RpcHandler,), {
            "store": bound_store,
            "done": collections.OrderedDict(),
            "inflight": {},
            "evicted": collections.OrderedDict(),
            "dedupe_lock": threading.Lock(),
            "tasks_lock": threading.Lock(),
            "auth_token": default_auth_token(auth_token),
            "collector": Collector(local_role="server",
                                   history=self.history),
            "ha": self.ha,
            # every docserver hosts the multi-tenant scheduler surface;
            # admission (tick) stays lease-fenced, so a board whose
            # admission runs in a separate runner process simply never
            # wins the lease here
            "scheduler": Scheduler(
                bound_store,
                config=scheduler_config or SchedulerConfig()),
        })
        self.store = handler.store
        self.collector = handler.collector
        self.scheduler = handler.scheduler
        try:
            self.httpd = http.server.ThreadingHTTPServer((host, port),
                                                         handler)
        except OSError:
            if self.ha is not None:
                # a replica that cannot serve must not contend for —
                # let alone hold — the board-primary lease
                self.ha.log.close()
            raise
        self.host, self.port = self.httpd.server_address[:2]
        if self.ha is not None:
            # bind the HTTP port FIRST: only a replica that can serve
            # may contend for the lease (a bind failure must not leak
            # a lease-holding controller that answers nothing).  The
            # handler's class-level dedupe maps are where replayed rid
            # answers land.
            self.ha.bind_handler(handler)
            self.ha.start()
        self._thread: Optional[threading.Thread] = None
        if self.alerts is not None:
            self._alert_stop = threading.Event()
            self._alert_thread = threading.Thread(
                target=self._alert_loop, daemon=True,
                name="alert-evaluator")
            self._alert_thread.start()

    def _alert_loop(self) -> None:
        """Evaluate + pump on the primary; standbys only tail the
        shared alert log so their /alertz stays live.  A sweep failure
        is loud and non-fatal — the next tick retries."""
        import logging

        from ..obs import alerts as _alerts

        while not self._alert_stop.wait(self._alert_interval):
            try:
                if self.ha is None or self.ha.is_primary():
                    _alerts.PLANE.evaluate(history=self.history,
                                           collector=self.collector)
                    _alerts.PLANE.pump()
                else:
                    _alerts.PLANE.refresh()
            except Exception as exc:
                logging.getLogger(__name__).warning(
                    "alert evaluator sweep failed: %s: %s",
                    type(exc).__name__, exc)

    @property
    def connstr(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start_background(self) -> "DocServer":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self.httpd.serve_forever()

    def shutdown(self) -> None:
        if self._alert_stop is not None:
            self._alert_stop.set()
            if self._alert_thread is not None:
                self._alert_thread.join(timeout=10)
        if self.alerts is not None:
            self.alerts.reset()
        self.httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=10)
        self.httpd.server_close()
        if self.ha is not None:
            # clean handoff: releases the board lease so a standby's
            # next poll promotes immediately, no expiry wait
            self.ha.stop()
        if self.history is not None:
            from ..obs import control as _control

            _control.LEDGER.unbind_history(self.history)
            self.history.close()


class HttpDocStore(DocStore):
    """Client DocStore over a :class:`DocServer` (``http://HOST:PORT``,
    or the HA replica-set form ``HOST:PORT,HOST:PORT``).

    One keep-alive connection per endpoint, serialized by a lock (a
    worker's claim loop and its heartbeat thread share the handle);
    re-established on a broken socket under the client's
    :class:`RetryPolicy`, with the request id making every re-send
    exactly-once for mutating ops.  With several endpoints the
    :class:`FailoverClient` rotates on transport failures and on a
    standby's 421 — the rid is allocated ONCE per logical call, so the
    re-send a failover triggers replays from the new primary's
    replicated dedupe table instead of re-applying.  The rid is
    ``SESSION:SEQ`` — a per-handle session plus a monotonic sequence —
    so the server can tell a straggling retry of an *evicted* dedupe
    entry from a fresh request and fail it loudly instead of silently
    re-applying (see ``_RpcHandler``).
    """

    def __init__(self, address: str,
                 auth_token: Optional[str] = None,
                 retry: Optional[RetryPolicy] = None) -> None:
        self._client = FailoverClient(
            address, what="http docstore", auth_token=auth_token,
            retry=retry)
        self._rid_session = uuid.uuid4().hex
        self._rid_seq = itertools.count(1)
        #: set after a server rejects find_and_modify_many as unknown —
        #: the client then falls back to serial claims for good
        self._no_batched_claims = False

        # serializes rid allocation WITH the send: the eviction watermark
        # assumes this session's seqs arrive in order, so two threads
        # sharing the handle (claim loop + heartbeat) must not allocate
        # seqs in one order and win the client's send lock in the other
        self._mutate_lock = threading.Lock()

    # the ACTIVE endpoint's coordinates (rotates under failover)
    @property
    def host(self) -> str:
        return self._client.host

    @property
    def port(self) -> int:
        return self._client.port

    def _rpc(self, op: str, **fields: Any) -> Any:
        payload: Dict[str, Any] = {"op": op, **fields}
        mutating = op in _MUTATING_OPS
        with self._mutate_lock if mutating else contextlib.nullcontext():
            if mutating:
                payload["rid"] = (f"{self._rid_session}:"
                                  f"{next(self._rid_seq)}")
            status, raw = self._client.request(
                "POST", "/rpc", body=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
        if status == 401:
            raise PermissionError(
                f"docstore rpc {op!r}: auth rejected by "
                f"{self.host}:{self.port} (set $MAPREDUCE_TPU_AUTH or "
                "pass auth to Connection)")
        if status == NOT_PRIMARY_STATUS:
            # single-endpoint store dialing a standby replica (a multi-
            # endpoint FailoverClient rotates before this can surface)
            raise NotPrimaryError(
                f"docstore rpc {op!r}: {self.host}:{self.port} is a "
                "standby board replica — pass every replica in the "
                "connstr (http://H1:P1,H2:P2) to fail over")
        if status != 200:
            raise IOError(f"docstore rpc {op!r}: HTTP {status}")
        reply = json.loads(raw)
        if not reply.get("ok"):
            exc_type = {"ValueError": ValueError, "KeyError": KeyError,
                        "TypeError": TypeError,
                        "PermissionError": PermissionError,
                        "DedupeEvictedError": DedupeEvictedError,
                        # a primary that self-fenced between the HTTP
                        # door and the write path answers in-body
                        "NotPrimaryError": NotPrimaryError,
                        }.get(reply.get("type"), IOError)
            raise exc_type(reply.get("error", "rpc failed"))
        return reply["result"]

    # -- DocStore interface ------------------------------------------------

    def insert(self, coll: str, doc: Doc) -> str:
        return self._rpc("insert", coll=coll, doc=doc)

    def insert_many(self, coll: str, docs: List[Doc]) -> List[str]:
        return self._rpc("insert_many", coll=coll, docs=docs)

    def find(self, coll: str, query: Optional[Query] = None) -> List[Doc]:
        return self._rpc("find", coll=coll, query=query)

    def count(self, coll: str, query: Optional[Query] = None) -> int:
        return self._rpc("count", coll=coll, query=query)

    def update(self, coll: str, query: Query, update: Doc,
               multi: bool = False, upsert: bool = False) -> int:
        return self._rpc("update", coll=coll, query=query, update=update,
                         multi=multi, upsert=upsert)

    def find_and_modify(self, coll: str, query: Query, update: Doc,
                        sort_key: Optional[Callable[[Doc], Any]] = None,
                        ) -> Optional[Doc]:
        if sort_key is not None:
            # callables don't cross the wire; no framework caller passes one
            raise NotImplementedError(
                "HttpDocStore.find_and_modify does not support sort_key")
        return self._rpc("find_and_modify", coll=coll, query=query,
                         update=update)

    def find_and_modify_many(self, coll: str, query: Query, update: Doc,
                             limit: int = 1) -> List[Doc]:
        if self._no_batched_claims:
            # a pre-batching server answered "unknown rpc op" once; keep
            # speaking its dialect (one claim per round trip)
            return DocStore.find_and_modify_many(self, coll, query,
                                                 update, limit)
        try:
            return self._rpc("find_and_modify_many", coll=coll,
                             query=query, update=update, limit=int(limit))
        except ValueError as exc:
            if "unknown rpc op" not in str(exc):
                raise
            self._no_batched_claims = True
            return DocStore.find_and_modify_many(self, coll, query,
                                                 update, limit)

    def remove(self, coll: str, query: Optional[Query] = None) -> int:
        return self._rpc("remove", coll=coll, query=query)

    def drop_collection(self, coll: str) -> None:
        self._rpc("drop_collection", coll=coll)

    def collections(self) -> List[str]:
        return self._rpc("collections")

    def ping(self) -> bool:
        return self._rpc("ping") == "pong"

    # -- exposition plane (the status CLI's feed) --------------------------

    def statusz(self) -> Dict[str, Any]:
        """Fetch the server's /statusz cluster snapshot."""
        status, raw = self._client.request("GET", "/statusz")
        if status == 401:
            raise PermissionError("statusz: auth rejected")
        if status != 200:
            raise IOError(f"statusz: HTTP {status}")
        return json.loads(raw)

    def metrics_text(self) -> str:
        """Fetch the server's /metrics Prometheus exposition."""
        status, raw = self._client.request("GET", "/metrics")
        if status == 401:
            raise PermissionError("metrics: auth rejected")
        if status != 200:
            raise IOError(f"metrics: HTTP {status}")
        return raw.decode()

    def tracez(self) -> Dict[str, Any]:
        """Fetch the server's /tracez Chrome trace snapshot (the
        ``profile`` CLI's bundle feed)."""
        status, raw = self._client.request("GET", "/tracez")
        if status == 401:
            raise PermissionError("tracez: auth rejected")
        if status != 200:
            raise IOError(f"tracez: HTTP {status}")
        return json.loads(raw)

    def clusterz(self) -> Dict[str, Any]:
        """Fetch the server's /clusterz merged cluster timeline (the
        ``timeline``/``diagnose`` CLI feed)."""
        status, raw = self._client.request("GET", "/clusterz")
        if status == 401:
            raise PermissionError("clusterz: auth rejected")
        if status != 200:
            raise IOError(f"clusterz: HTTP {status}")
        return json.loads(raw)

    def queryz(self, params: Dict[str, Any]) -> Dict[str, Any]:
        """Run one /queryz range query against the durable history
        plane (the ``history``/``top`` CLI feed).  *params* maps query
        parameter names to a value or a list of values (repeated
        ``match`` matchers)."""
        pairs: List[Tuple[str, str]] = []
        for k, v in params.items():
            for item in (v if isinstance(v, (list, tuple)) else (v,)):
                pairs.append((str(k), str(item)))
        qs = urllib.parse.urlencode(pairs)
        status, raw = self._client.request("GET", f"/queryz?{qs}")
        if status == 401:
            raise PermissionError("queryz: auth rejected")
        if status != 200:
            try:
                detail = json.loads(raw).get("error")
            except ValueError:
                detail = None
            raise IOError(f"queryz: HTTP {status}"
                          + (f" ({detail})" if detail else ""))
        return json.loads(raw)

    def alertz(self) -> Dict[str, Any]:
        """Fetch the alerting plane's lifecycle state (the ``alerts``
        CLI feed) — answered by standbys too, which is how an operator
        sees the same lifecycle after a failover."""
        status, raw = self._client.request("GET", "/alertz")
        if status == 401:
            raise PermissionError("alertz: auth rejected")
        if status != 200:
            try:
                detail = json.loads(raw).get("error")
            except ValueError:
                detail = None
            raise IOError(f"alertz: HTTP {status}"
                          + (f" ({detail})" if detail else ""))
        return json.loads(raw)

    def alert_op(self, op: str, rule: str,
                 duration: Optional[float] = None) -> Dict[str, Any]:
        """``silence`` / ``ack`` against the primary's alert plane."""
        req: Dict[str, Any] = {"op": op, "rule": rule}
        if duration is not None:
            req["duration"] = duration
        status, raw = self._client.request(
            "POST", "/alertz", body=json.dumps(req).encode())
        if status == 401:
            raise PermissionError("alertz: auth rejected")
        doc = json.loads(raw) if raw else {}
        if status != 200 or not doc.get("ok"):
            raise IOError(f"alertz {op}: HTTP {status}"
                          + (f" ({doc.get('error')})"
                             if doc.get("error") else ""))
        return doc.get("result") or {}

    def close(self) -> None:
        self._client.close()
