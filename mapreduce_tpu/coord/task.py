"""Task state machine: the singleton task document and job claiming.

Parity with mapreduce/task.lua: one task document (``_id="unique"``) per
database holding the phase (WAIT/MAP/REDUCE/FINISHED), the user module
names, storage spec, iteration counter and stats (task.lua:96-116, example
doc task.lua:26-56); job documents in ``map_jobs``/``red_jobs`` claimed
atomically by workers (task.lua:258-343).

Strengthened vs the reference (SURVEY.md §5 gaps):

  * claims use a real atomic ``find_and_modify`` instead of the racy
    update-then-find_one claim-by-stamp (task.lua:294-309, FIXME'd there);
  * RUNNING jobs carry a ``lease_expires`` wall-clock field; the server
    reaps expired leases back to BROKEN (the reference has no heartbeat or
    lease — dead workers' jobs hang until a server restart);
  * the map-job locality cache (task.lua:249-254, 279-293) is instance
    state, not a module global (quirk list, SURVEY.md §7).
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional, Tuple

from ..utils.constants import (
    STATUS, TASK_STATUS, DEFAULT_JOB_LEASE, MAX_IDLE_COUNT)
from . import docstore
from .connection import Connection

TaskDoc = Dict[str, Any]
JobDoc = Dict[str, Any]


class LeaseLostError(RuntimeError):
    """This worker's claim on the running job is gone (lease reaped after
    a partition outlasted it, or the job re-issued to another worker).
    Raised inside the job's execution path to abort it — the re-issued
    copy is now the authoritative run, and finishing here would race it
    (duplicate user-fn side effects, the window Dean & Ghemawat close by
    committing map output atomically; we close it at the source)."""


def make_job(key: Any, value: Any) -> JobDoc:
    """Build a claimable job document (reference utils.make_job:87-98)."""
    return {
        "_id": str(key),
        "key": key,
        "value": value,
        "worker": None,
        "status": int(STATUS.WAITING),
        "creation_time": docstore.now(),
        "repetitions": 0,
    }


class Task:
    """Reference: ``task(cnn)`` (task.lua:345-359)."""

    SINGLETON_ID = "unique"  # task.lua pins the doc id

    def __init__(self, connection: Connection,
                 job_lease: float = DEFAULT_JOB_LEASE) -> None:
        self._cnn = connection
        self.tbl: TaskDoc = {}
        self.job_lease = job_lease
        # locality cache: map-job ids this process wrote in a previous
        # iteration, preferred when re-claiming (task.lua:249-254)
        self._cached_map_ids: List[str] = []
        self._idle_count = 0

    # -- namespaces (task.lua:195-245) ------------------------------------

    def task_ns(self) -> str:
        return self._cnn.ns("task")

    def map_jobs_ns(self) -> str:
        return self._cnn.ns("map_jobs")

    def red_jobs_ns(self) -> str:
        return self._cnn.ns("red_jobs")

    def red_results_ns(self) -> str:
        return self.tbl.get("result_ns", self._cnn.ns("result"))

    def jobs_ns(self) -> str:
        """Collection for the *current* phase's jobs (task.lua:213-221)."""
        st = self.status()
        if st == TASK_STATUS.MAP:
            return self.map_jobs_ns()
        if st == TASK_STATUS.REDUCE:
            return self.red_jobs_ns()
        raise RuntimeError(f"no jobs collection in task status {st}")

    # -- task document lifecycle ------------------------------------------

    def create_collection(self, status: TASK_STATUS, params: Dict[str, Any],
                          iteration: int) -> None:
        """Write the task singleton (reference task.lua:96-116)."""
        doc = {
            "_id": self.SINGLETON_ID,
            "status": status.value,
            "iteration": iteration,
            "taskfn": params["taskfn"],
            "mapfn": params["mapfn"],
            "partitionfn": params["partitionfn"],
            "reducefn": params["reducefn"],
            "combinerfn": params.get("combinerfn"),
            "finalfn": params["finalfn"],
            "init_args": params.get("init_args"),
            "storage": params["storage"],
            "path": params["path"],
            "result_ns": params.get("result_ns", self._cnn.ns("result")),
            "device": bool(params.get("device", False)),
        }
        store = self._cnn.connect()
        store.update(self.task_ns(), {"_id": self.SINGLETON_ID}, doc,
                     upsert=True)
        self.tbl = dict(doc)

    def update(self) -> bool:
        """Re-read the singleton (task.lua:148-160); False if absent."""
        doc = self._cnn.connect().find_one(self.task_ns(),
                                           {"_id": self.SINGLETON_ID})
        if doc is None:
            return False
        self.tbl = doc
        return True

    def exists(self) -> bool:
        return bool(self.tbl) or self.update()

    def status(self) -> TASK_STATUS:
        return TASK_STATUS(self.tbl.get("status", "WAIT"))

    def iteration(self) -> int:
        return int(self.tbl.get("iteration", 0))

    def finished(self) -> bool:
        return self.status() == TASK_STATUS.FINISHED

    def set_task_status(self, status: TASK_STATUS) -> None:
        """task.lua:182-193."""
        self._cnn.connect().update(
            self.task_ns(), {"_id": self.SINGLETON_ID},
            {"$set": {"status": status.value}})
        self.tbl["status"] = status.value

    def set_fields(self, fields: Dict[str, Any]) -> None:
        self._cnn.connect().update(
            self.task_ns(), {"_id": self.SINGLETON_ID}, {"$set": fields})
        self.tbl.update(fields)

    def drop(self) -> None:
        self._cnn.connect().remove(self.task_ns(), {"_id": self.SINGLETON_ID})
        self.tbl = {}

    # -- job claiming (the scheduler heart) -------------------------------

    def insert_jobs(self, coll: str, jobs: List[JobDoc]) -> None:
        """Bulk job creation through the batched-insert path
        (server.lua:316-325 via cnn.annotate_insert)."""
        for j in jobs:
            self._cnn.annotate_insert(coll, j)
        self._cnn.flush_pending_inserts(0)

    def note_written_map_job(self, job_id: str) -> None:
        """Record a map-job id this process produced, for locality
        preference on later iterations (task.lua:313-318)."""
        self._cached_map_ids.append(job_id)

    def reset_locality(self) -> None:
        self._cached_map_ids = []
        self._idle_count = 0

    def take_next_job(self, worker_name: str, tmpname: str,
                      ) -> Tuple[Optional[JobDoc], TASK_STATUS]:
        """Atomically claim one job for *worker_name* (the serial form of
        :meth:`take_next_jobs`; kept for tests/tools and as the
        batch-size-1 path).

        Returns ``(job_doc, task_status)``; job_doc is None when there is
        nothing claimable (caller sleeps) or the task is WAIT/FINISHED.
        """
        got, st = self.take_next_jobs(worker_name, tmpname, 1)
        return (got[0] if got else None), st

    def take_next_jobs(self, worker_name: str, tmpname: str, n: int = 1,
                       ) -> Tuple[List[JobDoc], TASK_STATUS]:
        """Atomically claim up to *n* jobs for *worker_name* in ONE board
        round trip (find_and_modify_many, rid-deduped over http like any
        mutating RPC — a retried batch claim cannot double-claim).

        Every claimed doc carries the same ``(worker, tmpname)`` claim
        stamp; claim identity stays per-job because ``_id`` is part of
        the guard (job.Job._claim_query), so each claim in the batch is
        leased, heartbeated and FENCED independently of its batch-mates.
        Reference: task.lua:258-343 — including the iteration>1 locality
        preference (claim own cached map jobs first, then fall back to
        BROKEN-only for MAX_IDLE_COUNT polls, then anything).
        """
        n = max(int(n), 1)  # 0 would turn every poll into an idle poll
        if not self.update():
            return [], TASK_STATUS.WAIT
        st = self.status()
        if st in (TASK_STATUS.WAIT, TASK_STATUS.FINISHED):
            return [], st
        coll = self.jobs_ns()
        claimable = {"status": {"$in": [int(STATUS.WAITING),
                                        int(STATUS.BROKEN)]}}
        queries: List[Dict[str, Any]] = []
        if (st == TASK_STATUS.MAP and self.iteration() > 1
                and self._cached_map_ids):
            if self._idle_count < MAX_IDLE_COUNT:
                # prefer jobs whose output this host already has locally
                queries.append({**claimable,
                                "_id": {"$in": self._cached_map_ids}})
                queries.append({"status": int(STATUS.BROKEN)})
            else:
                queries.append(claimable)
        else:
            queries.append(claimable)

        now = docstore.now()
        claim = {"$set": {
            "worker": worker_name,
            "tmpname": tmpname,
            "started_time": now,
            "lease_expires": now + self.job_lease,
            "status": int(STATUS.RUNNING),
        }}
        store = self._cnn.connect()
        got: List[JobDoc] = []
        for q in queries:
            want = n - len(got)
            if want <= 0:
                break
            got.extend(store.find_and_modify_many(coll, q, claim, want))
        if got:
            self._idle_count = 0
        else:
            self._idle_count += 1
        return got, st

    def release_jobs(self, coll: str, job_tbls: List[JobDoc]) -> int:
        """Hand claimed-but-never-started jobs straight back to WAITING
        (claim-guarded, RUNNING only) so an exiting worker's claim-ahead
        queue is reclaimable immediately instead of after a lease reap —
        and without the spurious ``repetitions`` increment a reap charges.
        Best-effort: if this RPC fails the lease reaper covers it."""
        if not job_tbls:
            return 0
        guards = [{"_id": j["_id"], "worker": j.get("worker"),
                   "tmpname": j.get("tmpname"),
                   "status": int(STATUS.RUNNING)} for j in job_tbls]
        return self._cnn.connect().update(
            coll, {"$or": guards},
            {"$set": {"status": int(STATUS.WAITING), "worker": None}},
            multi=True)

    def heartbeat(self, job_tbl: JobDoc) -> bool:
        """Extend an in-flight job's lease (no reference equivalent — fixes
        the missing dead-worker detection, SURVEY.md §5).  Guarded by the
        claim identity so a stale worker can't extend a lease that now
        belongs to another worker's claim.  Matches both RUNNING and
        FINISHED: a map job is FINISHED while its worker is still writing
        output files (job.py), and that write phase must keep the lease
        alive too.

        Returns whether this claim still OWNS the job.  False means the
        lease was lost for certain — the server reaped it to BROKEN (a
        partition outlasted ``job_lease``) or another worker has since
        reclaimed it — and the caller must fence: abort the running job
        instead of racing the re-issued copy (the answer arrived over a
        working RPC, so False is knowledge, not a guess; a *network*
        failure raises instead and proves nothing either way).  WRITTEN
        is matched too: a beat racing this claim's own just-completed
        write must report ownership, not a spurious loss (the lease
        extension on a terminal doc is inert — the reaper only looks at
        RUNNING/FINISHED)."""
        n = self._cnn.connect().update(
            self.jobs_ns(),
            self._beat_guard(job_tbl),
            {"$set": {"lease_expires": docstore.now() + self.job_lease}})
        return n > 0

    @staticmethod
    def _beat_guard(job_tbl: JobDoc) -> Dict[str, Any]:
        return {"_id": job_tbl["_id"],
                "worker": job_tbl.get("worker"),
                "tmpname": job_tbl.get("tmpname"),
                "status": {"$in": [int(STATUS.RUNNING),
                                   int(STATUS.FINISHED),
                                   int(STATUS.WRITTEN)]}}

    def heartbeat_many(self, coll: str, job_tbls: List[JobDoc],
                       ) -> List[bool]:
        """Extend EVERY lease this worker holds (the running job plus its
        claim-ahead queue) in one ``$or``-guarded multi-update — one RPC
        per beat period however many claims are held.  Returns per-claim
        ownership, same semantics as :meth:`heartbeat`.

        Fencing stays per-claim: each ``$or`` arm is a full claim guard,
        so the update can only touch docs this worker still owns.  When
        the matched count says every claim is owned (the steady state)
        that single RPC is the whole answer; a shortfall means at least
        one lease is LOST, and each claim is then probed individually so
        exactly the lost ones get fenced — never the batch-mates that are
        still healthy.  *coll* is the jobs collection the batch was
        claimed from (passed explicitly: the task's phase may have moved
        on while these claims are still held)."""
        if not job_tbls:
            return []
        n = self._cnn.connect().update(
            coll, {"$or": [self._beat_guard(j) for j in job_tbls]},
            {"$set": {"lease_expires": docstore.now() + self.job_lease}},
            multi=True)
        if n >= len(job_tbls):
            return [True] * len(job_tbls)
        out = []
        for j in job_tbls:
            m = self._cnn.connect().update(
                coll, self._beat_guard(j),
                {"$set": {"lease_expires":
                          docstore.now() + self.job_lease}})
            out.append(m > 0)
        return out

    def reap_expired(self, coll: str) -> int:
        """Server-side: in-flight jobs (RUNNING, or FINISHED — user fn done
        but output files not yet written) with an expired lease become
        BROKEN (+1 repetition), making them claimable again.  FINISHED is
        non-terminal: a worker dying between mark_as_finished and
        mark_as_written would otherwise leave an unreapable job and hang
        the server's poll loop forever."""
        store = self._cnn.connect()
        n = 0
        while True:
            got = store.find_and_modify(
                coll,
                {"status": {"$in": [int(STATUS.RUNNING),
                                    int(STATUS.FINISHED)]},
                 "lease_expires": {"$lt": docstore.now()}},
                {"$set": {"status": int(STATUS.BROKEN)},
                 "$inc": {"repetitions": 1}})
            if got is None:
                return n
            n += 1

    @staticmethod
    def tmpname() -> str:
        """Per-claim scratch token (reference uses os.tmpname)."""
        return uuid.uuid4().hex[:12]
