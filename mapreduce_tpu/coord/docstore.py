"""Document store: the MongoDB-collection role of the reference's control
plane (cnn.lua + the ``task``/``map_jobs``/``red_jobs``/``errors``
collections, task.lua:349-352), without MongoDB.

Two backends behind one interface:

  * :class:`MemoryDocStore` — in-process dict + lock.  Unit tests and the
    single-process server+threads deployment use this; it is the "fake
    coordination backend so unit tests don't need a live service" the
    survey says the reference lacks (SURVEY.md §4).
  * :class:`DirDocStore` — one JSON file per document in a shared directory
    (local disk or NFS), cross-process atomicity from an ``fcntl`` lock
    file per collection and atomic tempfile+rename writes.  N OS-process
    workers on one host or a shared filesystem coordinate through it, the
    way the reference's workers coordinate through mongod.

The query/update language is the small Mongo subset the reference actually
uses (equality, ``$in``/``$lt``/``$gte``/``$ne``/``$exists``; ``$set``/
``$inc``/``$unset``/``$push``) — see e.g. the claim query task.lua:271-293
and ``mark_as_broken``'s ``$inc`` job.lua:322-342.  ``find_and_modify`` is
the one primitive the reference *wishes* it had for claims (it emulates it
with update-then-find_one, task.lua:294-309, with acknowledged races); here
it is genuinely atomic under the store lock.
"""

from __future__ import annotations

import copy
import fcntl
import json
import os
import threading
import time
import urllib.parse
import uuid
from typing import Any, Callable, Dict, Iterator, List, Optional

Doc = Dict[str, Any]
Query = Dict[str, Any]


# --- query / update language ------------------------------------------------

def _match_value(cond: Any, value: Any, present: bool) -> bool:
    if isinstance(cond, dict) and any(k.startswith("$") for k in cond):
        for op, arg in cond.items():
            if op == "$in":
                if value not in arg:
                    return False
            elif op == "$nin":
                if value in arg:
                    return False
            elif op == "$ne":
                if value == arg:
                    return False
            elif op == "$lt":
                if not (present and value is not None and value < arg):
                    return False
            elif op == "$lte":
                if not (present and value is not None and value <= arg):
                    return False
            elif op == "$gt":
                if not (present and value is not None and value > arg):
                    return False
            elif op == "$gte":
                if not (present and value is not None and value >= arg):
                    return False
            elif op == "$exists":
                if bool(present) != bool(arg):
                    return False
            else:
                raise ValueError(f"unsupported query operator {op!r}")
        return True
    return present and value == cond


def matches(doc: Doc, query: Query) -> bool:
    """True if *doc* satisfies *query* (Mongo-subset semantics)."""
    for field, cond in query.items():
        if field == "$or":
            if not any(matches(doc, q) for q in cond):
                return False
            continue
        present = field in doc
        if not _match_value(cond, doc.get(field), present):
            return False
    return True


def apply_update(doc: Doc, update: Doc) -> Doc:
    """Apply a Mongo-subset update spec to *doc* in place and return it.

    A spec with no ``$`` operators replaces the whole document except
    ``_id`` (Mongo replace semantics, used by task.lua:148-160 update).
    """
    if not any(k.startswith("$") for k in update):
        _id = doc.get("_id")
        doc.clear()
        doc.update(copy.deepcopy(update))
        if _id is not None and "_id" not in doc:
            doc["_id"] = _id
        return doc
    for op, fields in update.items():
        if op == "$set":
            for k, v in fields.items():
                doc[k] = copy.deepcopy(v)
        elif op == "$inc":
            for k, v in fields.items():
                doc[k] = doc.get(k, 0) + v
        elif op == "$unset":
            for k in fields:
                doc.pop(k, None)
        elif op == "$push":
            for k, v in fields.items():
                doc.setdefault(k, []).append(copy.deepcopy(v))
        else:
            raise ValueError(f"unsupported update operator {op!r}")
    return doc


# --- backends ---------------------------------------------------------------

class DocStore:
    """Abstract store of named collections of JSON-ish documents.

    Every mutating method takes the store-wide (Memory) or per-collection
    (Dir) lock, giving the single-document atomicity the reference leans on
    Mongo for (SURVEY.md §5 "Race detection": "safety relies on Mongo's
    single-document atomicity").
    """

    def insert(self, coll: str, doc: Doc) -> str:
        raise NotImplementedError

    def insert_many(self, coll: str, docs: List[Doc]) -> List[str]:
        return [self.insert(coll, d) for d in docs]

    def find(self, coll: str, query: Optional[Query] = None) -> List[Doc]:
        raise NotImplementedError

    def find_one(self, coll: str, query: Optional[Query] = None) -> Optional[Doc]:
        found = self.find(coll, query)
        return found[0] if found else None

    def update(self, coll: str, query: Query, update: Doc,
               multi: bool = False, upsert: bool = False) -> int:
        raise NotImplementedError

    def find_and_modify(self, coll: str, query: Query, update: Doc,
                        sort_key: Optional[Callable[[Doc], Any]] = None,
                        ) -> Optional[Doc]:
        """Atomically pick one matching doc, apply *update*, return the
        POST-update document (None if nothing matched)."""
        raise NotImplementedError

    def find_and_modify_many(self, coll: str, query: Query, update: Doc,
                             limit: int = 1) -> List[Doc]:
        """Claim up to *limit* matching docs in one call, applying
        *update* to each; returns the post-update documents (possibly
        empty).  The batched form of the worker claim — one round trip
        instead of *limit* (Task.take_next_jobs).  The base implementation
        loops :meth:`find_and_modify`, which is correct for any store
        whose claim update makes a doc stop matching (ours sets status
        RUNNING); backends override for one-lock atomicity."""
        out: List[Doc] = []
        for _ in range(max(int(limit), 0)):
            got = self.find_and_modify(coll, query, update)
            if got is None:
                break
            out.append(got)
        return out

    def remove(self, coll: str, query: Optional[Query] = None) -> int:
        raise NotImplementedError

    def count(self, coll: str, query: Optional[Query] = None) -> int:
        return len(self.find(coll, query))

    def drop_collection(self, coll: str) -> None:
        raise NotImplementedError

    def collections(self) -> List[str]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemoryDocStore(DocStore):
    """Dict-backed store; safe for many threads in one process.

    Instances are registered by name so that server and worker objects in
    one process can "connect" to the same store by connection string, the
    way reference processes all dial the same mongod (cnn.lua:34-39).
    """

    _registry: Dict[str, "MemoryDocStore"] = {}
    _registry_lock = threading.Lock()

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._colls: Dict[str, Dict[str, Doc]] = {}

    @classmethod
    def named(cls, name: str) -> "MemoryDocStore":
        with cls._registry_lock:
            if name not in cls._registry:
                cls._registry[name] = cls()
            return cls._registry[name]

    @classmethod
    def drop_named(cls, name: str) -> None:
        with cls._registry_lock:
            cls._registry.pop(name, None)

    def insert(self, coll: str, doc: Doc) -> str:
        with self._lock:
            d = copy.deepcopy(doc)
            _id = str(d.setdefault("_id", uuid.uuid4().hex))
            self._colls.setdefault(coll, {})[_id] = d
            return _id

    def find(self, coll: str, query: Optional[Query] = None) -> List[Doc]:
        with self._lock:
            docs = list(self._colls.get(coll, {}).values())
            if query:
                docs = [d for d in docs if matches(d, query)]
            return copy.deepcopy(docs)

    def update(self, coll: str, query: Query, update: Doc,
               multi: bool = False, upsert: bool = False) -> int:
        with self._lock:
            n = 0
            for d in self._colls.get(coll, {}).values():
                if matches(d, query):
                    apply_update(d, update)
                    n += 1
                    if not multi:
                        break
            if n == 0 and upsert:
                base = {k: v for k, v in query.items()
                        if not isinstance(v, dict) and not k.startswith("$")}
                # a doc with this _id existing but failing the query is a
                # conflict, not an upsert (Mongo raises duplicate-key);
                # overwriting would defeat optimistic-concurrency guards
                if "_id" in base and base["_id"] in self._colls.get(coll, {}):
                    return 0
                self.insert(coll, apply_update(base, update))
                n = 1
            return n

    def find_and_modify(self, coll, query, update, sort_key=None):
        with self._lock:
            docs = [d for d in self._colls.get(coll, {}).values()
                    if matches(d, query)]
            if not docs:
                return None
            if sort_key is not None:
                docs.sort(key=sort_key)
            d = apply_update(docs[0], update)
            return copy.deepcopy(d)

    def find_and_modify_many(self, coll, query, update, limit=1):
        with self._lock:
            out = []
            for d in self._colls.get(coll, {}).values():
                if len(out) >= limit:
                    break
                if matches(d, query):
                    out.append(copy.deepcopy(apply_update(d, update)))
            return out

    def remove(self, coll: str, query: Optional[Query] = None) -> int:
        with self._lock:
            table = self._colls.get(coll, {})
            if not query:
                n = len(table)
                table.clear()
                return n
            doomed = [k for k, d in table.items() if matches(d, query)]
            for k in doomed:
                del table[k]
            return len(doomed)

    def drop_collection(self, coll: str) -> None:
        with self._lock:
            self._colls.pop(coll, None)

    def collections(self) -> List[str]:
        with self._lock:
            return [c for c, t in self._colls.items() if t]


class DirDocStore(DocStore):
    """Shared-directory store: ``<root>/<collection>/<_id>.json`` per doc.

    Cross-process atomicity: every operation on a collection holds an
    ``fcntl.flock`` on ``<root>/<collection>.lock`` (blocking, exclusive);
    document writes are tempfile + ``os.rename`` so readers in *other*
    collections never see torn JSON.  This is the multi-process analogue of
    the reference's mongod and works on local disk or NFS-with-working-locks.
    """

    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._local_locks: Dict[str, threading.Lock] = {}
        self._llock = threading.Lock()
        self._fds: Dict[str, int] = {}
        self._closed = False

    def _coll_dir(self, coll: str) -> str:
        safe = coll.replace("/", "_")
        return os.path.join(self.root, safe)

    def _locked(self, coll: str) -> "_DirLock":
        with self._llock:
            tl = self._local_locks.setdefault(coll, threading.Lock())
        return _DirLock(self, coll, tl)

    def _read_all(self, coll: str) -> Dict[str, Doc]:
        d = self._coll_dir(coll)
        out: Dict[str, Doc] = {}
        if not os.path.isdir(d):
            return out
        for name in os.listdir(d):
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(d, name), "r") as f:
                    doc = json.load(f)
                out[doc["_id"]] = doc
            except (json.JSONDecodeError, OSError, KeyError):
                continue  # torn/garbage file: skip (writer uses atomic rename)
        return out

    def _write_doc(self, coll: str, doc: Doc) -> None:
        d = self._coll_dir(coll)
        os.makedirs(d, exist_ok=True)
        # _ids are arbitrary user keys (str(taskfn key), task.make_job) —
        # quote so "/" or ".." can't escape the collection directory
        safe_id = urllib.parse.quote(str(doc["_id"]), safe="")
        path = os.path.join(d, f"{safe_id}.json")
        tmp = path + f".tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.rename(tmp, path)

    def _delete_doc(self, coll: str, _id: str) -> None:
        safe_id = urllib.parse.quote(str(_id), safe="")
        try:
            os.remove(os.path.join(self._coll_dir(coll), f"{safe_id}.json"))
        except FileNotFoundError:
            pass

    def insert(self, coll: str, doc: Doc) -> str:
        with self._locked(coll):
            d = copy.deepcopy(doc)
            _id = str(d.setdefault("_id", uuid.uuid4().hex))
            d["_id"] = _id
            self._write_doc(coll, d)
            return _id

    def find(self, coll: str, query: Optional[Query] = None) -> List[Doc]:
        with self._locked(coll):
            docs = list(self._read_all(coll).values())
        if query:
            docs = [d for d in docs if matches(d, query)]
        return docs

    def update(self, coll: str, query: Query, update: Doc,
               multi: bool = False, upsert: bool = False) -> int:
        with self._locked(coll):
            n = 0
            for d in self._read_all(coll).values():
                if matches(d, query):
                    apply_update(d, update)
                    self._write_doc(coll, d)
                    n += 1
                    if not multi:
                        break
            if n == 0 and upsert:
                base = {k: v for k, v in query.items()
                        if not isinstance(v, dict) and not k.startswith("$")}
                # same duplicate-_id conflict rule as MemoryDocStore
                if "_id" in base and base["_id"] in self._read_all(coll):
                    return 0
                doc = apply_update(base, update)
                doc.setdefault("_id", uuid.uuid4().hex)
                self._write_doc(coll, doc)
                n = 1
            return n

    def find_and_modify(self, coll, query, update, sort_key=None):
        with self._locked(coll):
            docs = [d for d in self._read_all(coll).values()
                    if matches(d, query)]
            if not docs:
                return None
            if sort_key is not None:
                docs.sort(key=sort_key)
            d = apply_update(docs[0], update)
            self._write_doc(coll, d)
            return copy.deepcopy(d)

    def find_and_modify_many(self, coll, query, update, limit=1):
        with self._locked(coll):
            out = []
            for d in self._read_all(coll).values():
                if len(out) >= limit:
                    break
                if matches(d, query):
                    apply_update(d, update)
                    self._write_doc(coll, d)
                    out.append(copy.deepcopy(d))
            return out

    def remove(self, coll: str, query: Optional[Query] = None) -> int:
        with self._locked(coll):
            table = self._read_all(coll)
            doomed = [k for k, d in table.items()
                      if not query or matches(d, query)]
            for k in doomed:
                self._delete_doc(coll, k)
            return len(doomed)

    def drop_collection(self, coll: str) -> None:
        with self._locked(coll):
            d = self._coll_dir(coll)
            if os.path.isdir(d):
                for name in os.listdir(d):
                    try:
                        os.remove(os.path.join(d, name))
                    except FileNotFoundError:
                        pass
                try:
                    os.rmdir(d)
                except OSError:
                    pass

    def collections(self) -> List[str]:
        out = []
        for name in os.listdir(self.root):
            p = os.path.join(self.root, name)
            if os.path.isdir(p) and any(
                    f.endswith(".json") for f in os.listdir(p)):
                out.append(name)
        return out

    def close(self) -> None:
        # refuse new fd opens from this point on, then close every open fd
        # under ITS collection's thread lock — a _DirLock mid-critical-
        # section keeps its flock until __exit__, and a blocked one finds
        # the store closed instead of a stale/reused descriptor
        with self._llock:
            self._closed = True
        while True:
            with self._llock:
                coll = next(iter(self._fds), None)
                if coll is None:
                    return
                tlock = self._local_locks.setdefault(coll, threading.Lock())
            with tlock:
                with self._llock:
                    fd = self._fds.pop(coll, None)
                if fd is not None:
                    try:
                        os.close(fd)
                    except OSError:
                        pass


class _DirLock:
    """Thread lock + flock pair for one DirDocStore collection."""

    def __init__(self, store: DirDocStore, coll: str, tlock: threading.Lock):
        self.store, self.coll, self.tlock = store, coll, tlock

    def __enter__(self):
        self.tlock.acquire()
        try:
            path = os.path.join(self.store.root, f"{self.coll}.lock")
            fd = self.store._fds.get(self.coll)
            if fd is None:
                fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o644)
                # the closed-check and the registration must be one
                # critical section: otherwise a close() racing between
                # them would scan _fds before this entry lands, return,
                # and leave the "closed" store operable with a leaked fd
                with self.store._llock:
                    if self.store._closed:
                        os.close(fd)
                        raise RuntimeError("DirDocStore is closed")
                    self.store._fds[self.coll] = fd
            fcntl.flock(fd, fcntl.LOCK_EX)
        except BaseException:
            # never leave the thread lock held on a failed acquire —
            # that would deadlock every later op on this collection
            self.tlock.release()
            raise
        self.fd = fd
        return self

    def __exit__(self, *exc):
        fcntl.flock(self.fd, fcntl.LOCK_UN)
        self.tlock.release()
        return False


def connect(connstr: str, auth: Optional[str] = None,
            retry=None) -> DocStore:
    """Open a store from a connection string (reference: a mongod host:port,
    utils.lua:62-69).  Forms:

      * ``mem://<name>``       — process-local named MemoryDocStore
      * ``dir:///path``        — DirDocStore rooted at /path
      * ``/abs/path``          — shorthand for dir://
      * ``http://[TOKEN@]HOST:PORT`` — HttpDocStore dialing a DocServer
        (the cross-host topology: any worker anywhere joins with one
        connstr, like the reference's workers dialing one mongod).
        ``auth`` is the bearer token for an auth-required server
        (reference: the ``auth_table`` arg of cnn.lua:106-113); it can
        also ride the connstr or $MAPREDUCE_TPU_AUTH (httpclient.py).
        ``retry`` is an optional :class:`~..utils.httpclient.RetryPolicy`
        for the networked backend (ignored by the local ones, which have
        no wire to fail).
    """
    if connstr.startswith("mem://"):
        return MemoryDocStore.named(connstr[len("mem://"):])
    if connstr.startswith("dir://"):
        return DirDocStore(connstr[len("dir://"):])
    if connstr.startswith("http://"):
        from .docserver import HttpDocStore
        return HttpDocStore(connstr[len("http://"):], auth_token=auth,
                            retry=retry)
    if connstr.startswith("/"):
        return DirDocStore(connstr)
    raise ValueError(
        f"bad connection string {connstr!r} "
        "(want mem://NAME, dir:///PATH, or http://HOST:PORT)")


def now() -> float:
    """Wall-clock used for all lease / timing fields (reference uses
    mongo.time from the C module, utils.lua:78-84)."""
    return time.time()
