"""Engine-host fleet membership: leases, mesh facts, and task routes.

The board (PR 13) already survives its own death and the spill plane
already makes any single stream durable — but the serving tier was ONE
engine host.  This module is the reference's "dozens of workers can die
at any time" story applied to the device plane:

  * :class:`HostLease` — one generation-fenced lease document PER HOST
    in ``__fleet__.hosts`` (the coord/lease.py guarded-singleton
    machinery with the host id as the document id): a host that stops
    heartbeating is *expired*, a returning zombie's guarded writes
    match nothing once a sweep reaps it.
  * :class:`FleetMember` — the session-host handle: join (acquire),
    heartbeat liveness PLUS ``local_mesh_facts`` (compile-ledger
    warmth, worst-device HBM fraction) in one guarded write whose
    post-image answers the board's requests back (the ``drain`` flag),
    leave (clean release).
  * :class:`FleetRegistry` — the board/scheduler view: live vs expired
    hosts, the ``__fleet__.routes`` task->host table mutated only by
    guarded updates (a raced re-route resolves to exactly one winner),
    advisor sync (every live host's facts registered under its host id,
    dead hosts unregistered), and the guarded :meth:`~FleetRegistry.
    reap` that makes a failed-host sweep fire exactly once.
  * :func:`rehome_routes` — the shared move planner: score live hosts
    the way the AdmissionAdvisor scores meshes (warmth beats cold,
    headroom breaks ties, pressure penalized), re-route every stream of
    a dead/draining host, count each move and land it in the control
    ledger.  The recovery sweep (sched/scheduler.py) and ``cli drain``
    are both one call to this.

Durability contract: routes and host docs live on the SAME board the
job collections ride (mem/dir/http), so fleet state survives any
process death the board survives; the streams themselves are durable in
the spill store, and restore is lazy — a re-homed stream costs nothing
until its next touch.

Monotonic-only module (AST-linted): lease waits are durations; every
persisted stamp (lease expiry, facts age, route moves) is minted
through coord/docstore.now like the rest of the board.
"""

from __future__ import annotations

import os
import socket
from typing import Any, Dict, List, Optional, Tuple

from ..obs import metrics as _metrics
from . import docstore
from .lease import TrainerLease
from .task import LeaseLostError

#: reserved database prefix for fleet state on the board
FLEET_DB = "__fleet__"
HOSTS_COLL = f"{FLEET_DB}.hosts"
ROUTES_COLL = f"{FLEET_DB}.routes"

#: default host lease (seconds) — the failed-host detection window: a
#: SIGKILLed engine host's streams are re-homeable one of these after
#: its last beat.  Hosts beat every serve-loop turn (~1s), so this
#: tolerates a few missed beats without flapping.
DEFAULT_HOST_LEASE = 5.0

#: a host at or above this HBM fraction is pressure-penalized as a
#: re-home destination (the AdmissionAdvisor.PRESSURE_FRAC policy,
#: restated here so coord/ stays free of engine imports)
PRESSURE_FRAC = 0.8

_HOSTS = _metrics.gauge(
    "mrtpu_fleet_hosts",
    "registered engine hosts by membership state (labels: state="
    "live|draining|expired|left) — whole-family swap at every "
    "fleet snapshot and registry sweep")
_BEATS = _metrics.counter(
    "mrtpu_fleet_heartbeats_total",
    "engine-host fleet heartbeats (labels: host, outcome=owned|lost) "
    "— 'lost' is DEFINITIVE (the guarded write matched nothing over a "
    "working RPC): the host has been reaped or superseded and must "
    "stop serving")
_RECOVERIES = _metrics.counter(
    "mrtpu_fleet_recoveries_total",
    "failed-host recovery sweeps that re-homed an expired host's "
    "streams (labels: host) — one increment per reaped host, however "
    "many streams moved")
_MIGRATIONS = _metrics.counter(
    "mrtpu_session_migrations_total",
    "live session migrations between engine hosts (labels: task, "
    "reason=explicit|rebalance|drain|recovery) — every migration is "
    "spill-on-src + guarded route flip + lazy restore-on-dst, and "
    "every one lands in the control ledger (controller=fleet)")


class HostFencedError(LeaseLostError):
    """This engine host's fleet lease is definitively gone (expired and
    reaped by a recovery sweep, or superseded): its streams may already
    be re-homed — the host must stop serving them and rejoin as a
    fresh member."""


def default_host_id() -> str:
    """The unique per-process host id (``hostname:pid``) — two runners
    on one board must not clobber each other's membership or
    ``register_mesh`` facts."""
    return f"{socket.gethostname()}:{os.getpid()}"


class _FleetCnn:
    """Minimal Connection shape over a raw DocStore (connect() + ns())
    so fleet leases ride any board the caller already holds."""

    def __init__(self, store: docstore.DocStore) -> None:
        self._store = store

    def connect(self) -> docstore.DocStore:
        return self._store

    def ns(self, coll: str) -> str:
        return f"{FLEET_DB}.{coll}"


class HostLease(TrainerLease):
    """One engine host's membership lease: coord/lease.py's guarded
    document (seed-iff-absent, free-or-expired claim, ``$inc``
    generation fencing token) with the HOST ID as the document id —
    N hosts, N independent lease docs in ``__fleet__.hosts``.  Beats
    and fences count in the shared trainer-lease metric family."""

    COLL = "hosts"

    def __init__(self, cnn, host_id: str,
                 holder: Optional[str] = None,
                 lease: float = DEFAULT_HOST_LEASE) -> None:
        super().__init__(
            cnn,
            holder=holder or f"host-{host_id}",
            lease=lease)
        #: instance-level shadow of the class attribute: every guarded
        #: query in TrainerLease goes through self.SINGLETON_ID, so
        #: this one assignment points the whole machinery at our doc
        self.SINGLETON_ID = str(host_id)


class FleetMember:
    """The session-host side of the fleet: join, beat facts, leave.

    The heartbeat is ONE guarded ``find_and_modify`` that extends the
    lease and refreshes the host's placement facts, and whose returned
    post-image carries the board's requests back (today: the ``drain``
    flag ``cli drain`` sets) — membership, telemetry and control ride
    a single board round-trip per beat."""

    def __init__(self, store: docstore.DocStore,
                 host_id: Optional[str] = None,
                 lease: float = DEFAULT_HOST_LEASE,
                 holder: Optional[str] = None) -> None:
        self.store = store
        self.host_id = str(host_id or default_host_id())
        self.lease = HostLease(_FleetCnn(store), self.host_id,
                               holder=holder, lease=lease)

    @property
    def generation(self) -> Optional[int]:
        return self.lease.generation

    def join(self, timeout: Optional[float] = None,
             warm_programs=(), hbm_frac: Optional[float] = None) -> int:
        """Acquire this host's lease (blocking up to *timeout*; a dead
        predecessor under the same id is waited out) and publish the
        first facts; returns the fencing generation."""
        gen = self.lease.acquire(timeout=timeout)
        self.heartbeat(warm_programs=warm_programs, hbm_frac=hbm_frac)
        return gen

    def heartbeat(self, warm_programs=None,
                  hbm_frac: Optional[float] = None,
                  ) -> Optional[Dict[str, Any]]:
        """Extend the lease and (when given) refresh the host's mesh
        facts; returns the post-image host doc — ``doc["drain"]`` is
        the board asking this host to migrate off and leave — or None
        on DEFINITIVE loss (reaped/superseded; the host must fence)."""
        if self.lease.generation is None:
            return None
        sets: Dict[str, Any] = {
            "lease_expires": docstore.now() + self.lease.lease}
        if warm_programs is not None or hbm_frac is not None:
            sets["facts"] = {
                "warm": sorted(str(p) for p in (warm_programs or ())),
                "hbm_frac": None if hbm_frac is None
                else float(hbm_frac),
            }
            sets["facts_time"] = docstore.now()
        doc = self.store.find_and_modify(
            self.lease.ns, self.lease._guard(), {"$set": sets})
        _BEATS.inc(host=self.host_id,
                   outcome="owned" if doc is not None else "lost")
        if doc is None:
            self.lease.generation = None
        return doc

    def ensure_member(self) -> Dict[str, Any]:
        """Heartbeat that raises :class:`HostFencedError` on definitive
        loss — the serve-loop gate (the ``ensure_owned`` shape)."""
        doc = self.heartbeat()
        if doc is None:
            raise HostFencedError(
                f"host {self.host_id!r} lost its fleet lease: a "
                "recovery sweep may have re-homed its streams — stop "
                "serving and rejoin")
        return doc

    def leave(self) -> bool:
        """Clean departure: clear the holder so the host shows as left
        (not expired) and a successor under the same id joins with no
        reap wait."""
        return self.lease.release()


def host_state(doc: Dict[str, Any], now: float) -> str:
    """Classify one host doc against the board clock *now* (a wall
    stamp minted by docstore.now — the /statusz lease-view license)."""
    if doc.get("holder") is None:
        return "left"
    if float(doc.get("lease_expires") or 0.0) <= now:
        return "expired"
    return "draining" if doc.get("drain") else "live"


class FleetRegistry:
    """The board-side fleet view: membership, routes, advisor sync."""

    def __init__(self, store: docstore.DocStore) -> None:
        self.store = store

    # -- membership --------------------------------------------------------

    def hosts(self) -> List[Dict[str, Any]]:
        return self.store.find(HOSTS_COLL)

    def _by_state(self, state: str,
                  now: Optional[float] = None) -> List[Dict[str, Any]]:
        now = docstore.now() if now is None else now
        return [d for d in self.hosts() if host_state(d, now) == state]

    def live_hosts(self, now: Optional[float] = None,
                   ) -> List[Dict[str, Any]]:
        """Hosts holding an unexpired lease (draining hosts count:
        they still serve until their drain completes, they are only
        excluded as re-home DESTINATIONS)."""
        now = docstore.now() if now is None else now
        return [d for d in self.hosts()
                if host_state(d, now) in ("live", "draining")]

    def expired_hosts(self, now: Optional[float] = None,
                      ) -> List[Dict[str, Any]]:
        """Hosts whose lease lapsed without a release — the recovery
        sweep's input (a cleanly-left host is NOT here: its streams
        were drained before release)."""
        return self._by_state("expired", now)

    def request_drain(self, host_id: str) -> bool:
        """Ask *host_id* to migrate everything off and leave: the flag
        rides back on its next heartbeat's post-image."""
        return self.store.update(
            HOSTS_COLL, {"_id": str(host_id)},
            {"$set": {"drain": True,
                      "drain_time": docstore.now()}}) > 0

    def reap(self, doc: Dict[str, Any]) -> bool:
        """Guarded burial of an expired host: clears the holder ONLY if
        the doc still matches the (holder, generation) the sweep saw —
        two racing sweeps reap once, and a zombie host's next guarded
        heartbeat matches nothing (it fences instead of resurrecting a
        re-homed fleet slice)."""
        return self.store.update(
            HOSTS_COLL,
            {"_id": doc["_id"], "holder": doc.get("holder"),
             "generation": doc.get("generation")},
            {"$set": {"holder": None, "lease_expires": 0.0,
                      "drain": False,
                      "reaped_time": docstore.now()}}) > 0

    # -- task -> host routes -----------------------------------------------

    def route(self, task: str) -> Optional[Dict[str, Any]]:
        return self.store.find_one(ROUTES_COLL, {"_id": str(task)})

    def routes_for(self, host_id: str) -> List[Dict[str, Any]]:
        return self.store.find(ROUTES_COLL, {"host": str(host_id)})

    def assign(self, task: str, host_id: str,
               program: Optional[str] = None,
               reason: str = "place") -> None:
        """Place *task* on *host_id* (fresh streams; an existing route
        is re-pointed — placement is the scheduler's call to make).
        *program* is remembered so later re-homes can score warmth."""
        sets: Dict[str, Any] = {"host": str(host_id),
                                "moved_time": docstore.now(),
                                "reason": str(reason)}
        if program is not None:
            sets["program"] = str(program)
        self.store.update(ROUTES_COLL, {"_id": str(task)},
                          {"$set": sets}, upsert=True)

    def reroute(self, task: str, dst_host: str,
                expect_src: Optional[str] = None) -> bool:
        """Guarded route flip: wins only while the route still points
        at *expect_src* (when given) — a migration racing a recovery
        sweep resolves to exactly one move."""
        guard: Dict[str, Any] = {"_id": str(task)}
        if expect_src is not None:
            guard["host"] = str(expect_src)
        return self.store.find_and_modify(
            ROUTES_COLL, guard,
            {"$set": {"host": str(dst_host),
                      "moved_time": docstore.now()}}) is not None

    def drop_route(self, task: str) -> None:
        self.store.remove(ROUTES_COLL, {"_id": str(task)})

    # -- advisor sync ------------------------------------------------------

    def sync_advisor(self, advisor,
                     now: Optional[float] = None) -> None:
        """Mirror the fleet into an AdmissionAdvisor: every live host's
        heartbeat facts registered under its host id, every dead/left
        host unregistered — the scheduler's placement is then over the
        REAL fleet, not one advisory mesh.  Entries the advisor holds
        that never were fleet hosts (an embedder's own register_mesh)
        are left alone."""
        if advisor is None:
            return
        now = docstore.now() if now is None else now
        docs = {str(d["_id"]): d for d in self.hosts()}
        for host_id, doc in sorted(docs.items()):
            facts = doc.get("facts") or {}
            if host_state(doc, now) in ("live", "draining"):
                advisor.register_mesh(
                    host_id, warm_programs=facts.get("warm") or (),
                    hbm_frac=facts.get("hbm_frac"))
            else:
                advisor.unregister_mesh(host_id)


def _score_host(doc: Dict[str, Any],
                program: Optional[str]) -> Tuple[float, Dict[str, Any]]:
    """AdmissionAdvisor's mesh score over a host doc's heartbeat facts:
    warm beats cold, headroom breaks ties, pressure penalized."""
    facts = doc.get("facts") or {}
    warm = (program is not None
            and str(program) in set(facts.get("warm") or ()))
    frac = facts.get("hbm_frac")
    frac = None if frac is None else float(frac)
    headroom = 1.0 - (0.5 if frac is None
                      else min(max(frac, 0.0), 1.0))
    score = (2.0 if warm else 0.0) + headroom
    if frac is not None and frac >= PRESSURE_FRAC:
        score -= 2.0
    return score, {"warm": warm, "hbm_frac": frac,
                   "score": round(score, 4)}


def rehome_routes(registry: FleetRegistry, src_host: str,
                  reason: str, ledger=None,
                  now: Optional[float] = None,
                  ) -> List[Tuple[str, str]]:
    """Move every stream routed at *src_host* to the best live host
    (excluding *src_host* and draining hosts): the route flips are
    guarded (a stream someone else already moved is skipped, not
    stolen), each move is counted in ``mrtpu_session_migrations_total``
    and recorded as a control-ledger ``fleet`` decision.  Returns the
    ``(task, dst_host)`` moves made.  The streams themselves need no
    touch — they are durable in the spill store and restore lazily on
    the destination's next feed/snapshot."""
    now = docstore.now() if now is None else now
    candidates = [d for d in registry.live_hosts(now)
                  if str(d["_id"]) != str(src_host)
                  and host_state(d, now) == "live"]
    routes = registry.routes_for(src_host)
    if not routes:
        return []
    if not candidates:
        # nowhere to go: the streams stay routed at the dead host and
        # the NEXT sweep (with a live host back) moves them — durable
        # state means deferral, never loss.  Loud, because a fleet
        # with zero live hosts is an operator page, not a detail.
        if ledger is not None:
            ledger.record(
                "fleet", "-",
                {"src": str(src_host), "streams": len(routes),
                 "live_candidates": 0},
                {"reason": str(reason), "deferred": True},
                outcome="refused",
                note=f"cannot re-home {len(routes)} stream(s) off "
                     f"{src_host}: no live destination host")
        return []
    moves: List[Tuple[str, str]] = []
    for rt in sorted(routes, key=lambda r: str(r["_id"])):
        task = str(rt["_id"])
        program = rt.get("program")
        scored = {str(d["_id"]): _score_host(d, program)
                  for d in candidates}
        dst = max(scored, key=lambda h: (scored[h][0], h))
        if not registry.reroute(task, dst, expect_src=src_host):
            continue  # raced another mover: its flip stands
        _MIGRATIONS.inc(task=task, reason=str(reason))
        moves.append((task, dst))
        if ledger is not None:
            ledger.record(
                "fleet", task,
                {"src": str(src_host), "program": program,
                 "candidates": {h: s[1] for h, s in scored.items()}},
                {"dst": dst, "reason": str(reason)},
                outcome="applied",
                note=f"re-homed {task} off {src_host} to {dst} "
                     f"({reason}, "
                     + ("warm" if scored[dst][1]["warm"] else "cold")
                     + ")")
    return moves


def fleet_snapshot(store: docstore.DocStore,
                   now: Optional[float] = None) -> Dict[str, Any]:
    """The /statusz fleet section: per-host membership state, lease
    headroom, heartbeat facts and resident-route counts, plus the
    route total.  Empty when no host ever joined (the section stays
    off the page).  Refreshes the ``mrtpu_fleet_hosts`` gauge family
    as a side effect, so a /metrics scrape is always current."""
    docs = store.find(HOSTS_COLL)
    routes = store.find(ROUTES_COLL)
    if not docs and not routes:
        return {}
    now = docstore.now() if now is None else now
    hosts: Dict[str, Dict[str, Any]] = {}
    counts: Dict[str, int] = {}
    for d in docs:
        state = host_state(d, now)
        counts[state] = counts.get(state, 0) + 1
        facts = d.get("facts") or {}
        hosts[str(d["_id"])] = {
            "state": state,
            "generation": int(d.get("generation") or 0),
            "lease_expires_in": round(
                float(d.get("lease_expires") or 0.0) - now, 3),
            "warm_programs": len(facts.get("warm") or ()),
            "hbm_frac": facts.get("hbm_frac"),
            "streams": 0,
        }
    unrouted = 0
    for rt in routes:
        h = hosts.get(str(rt.get("host")))
        if h is None:
            unrouted += 1
        else:
            h["streams"] += 1
    _HOSTS.replace([({"state": s}, n)
                    for s, n in sorted(counts.items())])
    out: Dict[str, Any] = {"hosts": hosts, "routes": len(routes)}
    if unrouted:
        out["routes_unhosted"] = unrouted
    return out
