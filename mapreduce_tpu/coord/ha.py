"""Board high availability: a replicated, lease-elected docserver.

The reference survives any single process dying because MongoDB *is*
the control plane — kill a worker, the board lives on.  The rebuild's
board was one in-process :class:`~.docstore.MemoryDocStore` inside one
docserver: kill that and every lease, claim and dedupe entry died with
it.  This module gives the board the mongod property back, from three
pieces that already existed elsewhere in the tree:

* **Durable mutation log** (:class:`~.persistent_table.MutationLog`):
  :class:`ReplicatedDocStore` wraps the authoritative MemoryDocStore
  and appends every mutation — with its rid and the writer's fencing
  generation — to one shared append-only JSONL file.  Application
  order IS log order (one critical section around apply + append), so
  a replay reproduces the primary's document state exactly; ``insert``
  ids are assigned BEFORE logging and id-less upserts decompose into a
  logged insert, so replay is deterministic.
* **Board-primary lease** (:class:`~.lease.BoardLease`): the
  coord/lease.py seed-iff-absent / free-or-expired / ``$inc``
  generation pattern, pointed at a tiny :class:`~.docstore.DirDocStore`
  inside the HA directory — the one store that must not live on the
  board it elects.  The holder self-fences on its own monotonic clock
  (writes refuse once ``last-renewal + lease`` passes without a
  successful heartbeat), the standby only claims after the persisted
  expiry, and every log entry carries the writer's generation so a
  deposed primary's straggling appends are skipped on replay.
* **Replicated dedupe**: each answered mutating RPC's ``SESSION:SEQ``
  rid and recorded response body land in the SAME atomic log write as
  its mutation entries (:meth:`ReplicatedDocStore.deferred_rid`), so a
  client retry that fails over to the new primary replays the recorded
  answer instead of re-applying — exactly-once holds by construction
  across the failover.  A rid whose mutations were logged but whose
  response never was (the writer died mid-request) is refused with the
  dedupe plane's loud-ambiguity error, never silently re-applied.

Deployment: N ``docserver --ha-dir DIR`` replicas over one shared
directory (local disk for one host, NFS across hosts).  Exactly one
holds the lease and serves; the rest answer HTTP 421 (NOT retryable —
clients rotate instantly) and tail the log.  Kill the primary —
SIGKILL, mid-stream — and a standby finishes the replay and takes over
within one lease period.  A single replica over an HA dir is simply a
DURABLE board: restart it and it replays itself back.
"""

from __future__ import annotations

import contextlib
import copy
import logging
import os
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from ..obs import metrics as _metrics
from ..utils.httpclient import NotPrimaryError
from . import docstore as _ds
from .docstore import Doc, DocStore, MemoryDocStore, Query
from .lease import DEFAULT_BOARD_LEASE, BoardLease
from .persistent_table import BoardLogCorruptError, MutationLog

logger = logging.getLogger("mapreduce_tpu.ha")

_LOG_ENTRIES = _metrics.counter(
    "mrtpu_board_log_entries_total",
    "board mutation-log entries (labels: dir=append|replay|"
    "skipped_stale — skipped_stale counts a deposed primary's "
    "straggling appends discarded by generation fencing)")
_PROMOTIONS = _metrics.counter(
    "mrtpu_board_promotions_total",
    "times this replica took the board-primary lease over")
_BOARD_FENCES = _metrics.counter(
    "mrtpu_board_fences_total",
    "times this replica definitively lost the board-primary lease "
    "and demoted itself (its replica is rebuilt from the log)")
_REFUSED_RIDS = _metrics.counter(
    "mrtpu_board_replayed_rid_refusals_total",
    "rids whose mutations were in the log without a recorded response "
    "at promotion (the old primary died mid-request): their retries "
    "are refused with the loud dedupe ambiguity, never re-applied")
_IS_PRIMARY = _metrics.gauge(
    "mrtpu_board_primary",
    "1 while this replica holds (and can still prove, on its own "
    "monotonic clock) the board-primary lease, else 0")
_GENERATION = _metrics.gauge(
    "mrtpu_board_generation",
    "fencing generation of this replica's current/last primacy")
_REPLAY_LAG = _metrics.gauge(
    "mrtpu_board_replay_lag_bytes",
    "bytes of the shared mutation log this replica has not applied "
    "yet (0 on the primary by construction)")


class _StoreCnn:
    """Connection shape (connect()/ns()) over the HA dir's lease store."""

    def __init__(self, store: DocStore) -> None:
        self._store = store

    def connect(self) -> DocStore:
        return self._store

    def ns(self, coll: str) -> str:
        return f"__board__.{coll}"


class _RidTxn:
    """One rid-carrying request's deferred log write: every mutation
    the request applies buffers here, and the committed response body
    joins them in ONE atomic append at scope exit."""

    __slots__ = ("rid", "entries", "body")

    def __init__(self, rid: str) -> None:
        self.rid = rid
        self.entries: List[Dict[str, Any]] = []
        self.body: Optional[bytes] = None


def apply_entry(store: DocStore, entry: Dict[str, Any]) -> None:
    """Replay ONE logged mutation onto *store* (the replica's inner
    MemoryDocStore).  ``resp`` entries are the caller's (dedupe plane),
    not ours."""
    op = entry["op"]
    coll = entry.get("coll")
    if op == "insert":
        store.insert(coll, entry["doc"])
    elif op == "insert_many":
        store.insert_many(coll, entry["docs"])
    elif op == "update":
        store.update(coll, entry["q"], entry["u"],
                     multi=bool(entry.get("m")),
                     upsert=bool(entry.get("up")))
    elif op == "fam":
        store.find_and_modify(coll, entry["q"], entry["u"])
    elif op == "fam_many":
        store.find_and_modify_many(coll, entry["q"], entry["u"],
                                   int(entry.get("lim", 1)))
    elif op == "remove":
        store.remove(coll, entry.get("q"))
    elif op == "drop":
        store.drop_collection(coll)
    elif op == "noop":
        pass  # promotion fence marker: raises the generation bar only
    else:
        raise BoardLogCorruptError(
            f"board log entry with unknown op {op!r}")


class ReplicatedDocStore(DocStore):
    """The primary's store: every mutation applies to the inner
    MemoryDocStore and lands in the shared mutation log inside ONE
    critical section, so log order is application order and a replay
    is exact.  Reads pass straight through.

    Mutations carry the holder's fencing generation and refuse with
    :class:`~..utils.httpclient.NotPrimaryError` once the controller
    can no longer prove primacy (standby, fenced, or the local
    monotonic lease-validity window lapsed) — the write path itself is
    fenced, not just the HTTP front door.
    """

    def __init__(self, inner: Optional[MemoryDocStore] = None,
                 log: Optional[MutationLog] = None,
                 gen_fn: Optional[Callable[[], int]] = None,
                 fence: Optional[Callable[[], None]] = None) -> None:
        self.inner = inner if inner is not None else MemoryDocStore()
        self.log = log
        self._gen_fn = gen_fn or (lambda: 0)
        self._fence = fence or (lambda: None)
        self._lock = threading.RLock()
        self._seq = 0
        self._tls = threading.local()

    # -- the deferred-rid transaction ------------------------------------

    @contextlib.contextmanager
    def deferred_rid(self, rid: str):
        """Scope one rid-carrying request: mutations inside buffer
        their log entries on the transaction instead of appending
        one-by-one; scope exit appends them PLUS the recorded response
        (``txn.body``, when the handler set one) as a single atomic
        log write.  The store lock is held for the whole scope, so no
        other writer's entries can interleave between this request's
        application and its log record."""
        with self._lock:
            prev = getattr(self._tls, "txn", None)
            txn = _RidTxn(rid)
            self._tls.txn = txn
            try:
                yield txn
            finally:
                self._tls.txn = prev
                entries = txn.entries
                if txn.body is not None:
                    entries.append(self._stamp(
                        {"op": "resp", "rid": rid,
                         "body": txn.body.decode("utf-8", "replace")}))
                if entries and self.log is not None:
                    self.log.append_many(entries)
                    _LOG_ENTRIES.inc(len(entries), dir="append")

    def _stamp(self, entry: Dict[str, Any]) -> Dict[str, Any]:
        self._seq += 1
        entry["g"] = int(self._gen_fn())
        entry["s"] = self._seq
        return entry

    def _record(self, entry: Dict[str, Any]) -> None:
        """Log one mutation: buffered on the open rid transaction, or
        appended immediately (server-side writers — the hosted
        scheduler — have no rid)."""
        entry = self._stamp(entry)
        txn = getattr(self._tls, "txn", None)
        if txn is not None:
            if txn.rid:
                entry.setdefault("rid", txn.rid)
            txn.entries.append(entry)
        elif self.log is not None:
            self.log.append_many([entry])
            _LOG_ENTRIES.inc(dir="append")

    # -- reads (passthrough) ---------------------------------------------

    def find(self, coll: str, query: Optional[Query] = None) -> List[Doc]:
        return self.inner.find(coll, query)

    def count(self, coll: str, query: Optional[Query] = None) -> int:
        return self.inner.count(coll, query)

    def collections(self) -> List[str]:
        return self.inner.collections()

    # -- mutations (fenced + logged) --------------------------------------

    def insert(self, coll: str, doc: Doc) -> str:
        with self._lock:
            self._fence()
            d = copy.deepcopy(doc)
            # assign the id HERE so the logged doc replays to the same
            # one (the inner store's uuid fallback would diverge)
            d["_id"] = str(d.get("_id") or uuid.uuid4().hex)
            _id = self.inner.insert(coll, d)
            self._record({"op": "insert", "coll": coll, "doc": d})
            return _id

    def insert_many(self, coll: str, docs: List[Doc]) -> List[str]:
        with self._lock:
            self._fence()
            ds = []
            for doc in docs:
                d = copy.deepcopy(doc)
                d["_id"] = str(d.get("_id") or uuid.uuid4().hex)
                ds.append(d)
            ids = self.inner.insert_many(coll, ds)
            self._record({"op": "insert_many", "coll": coll, "docs": ds})
            return ids

    def update(self, coll: str, query: Query, update: Doc,
               multi: bool = False, upsert: bool = False) -> int:
        with self._lock:
            self._fence()
            if upsert and "_id" not in query:
                # an id-less upsert's inserted doc would get a store-
                # generated uuid replay cannot reproduce: decompose
                # into update-miss + an explicitly-id'd logged insert
                # (same semantics as MemoryDocStore.update's upsert)
                n = self.inner.update(coll, query, update, multi=multi,
                                      upsert=False)
                if n:
                    self._record({"op": "update", "coll": coll,
                                  "q": query, "u": update,
                                  "m": bool(multi)})
                    return n
                base = {k: v for k, v in query.items()
                        if not isinstance(v, dict)
                        and not k.startswith("$")}
                doc = _ds.apply_update(base, copy.deepcopy(update))
                doc["_id"] = str(doc.get("_id") or uuid.uuid4().hex)
                self.inner.insert(coll, doc)
                self._record({"op": "insert", "coll": coll, "doc": doc})
                return 1
            n = self.inner.update(coll, query, update, multi=multi,
                                  upsert=upsert)
            if n:
                self._record({"op": "update", "coll": coll, "q": query,
                              "u": update, "m": bool(multi),
                              "up": bool(upsert)})
            return n

    def find_and_modify(self, coll: str, query: Query, update: Doc,
                        sort_key: Optional[Callable[[Doc], Any]] = None,
                        ) -> Optional[Doc]:
        if sort_key is not None:
            raise NotImplementedError(
                "a replicated board cannot log a sort_key callable; "
                "no framework caller passes one")
        with self._lock:
            self._fence()
            got = self.inner.find_and_modify(coll, query, update)
            if got is not None:
                self._record({"op": "fam", "coll": coll, "q": query,
                              "u": update})
            return got

    def find_and_modify_many(self, coll: str, query: Query, update: Doc,
                             limit: int = 1) -> List[Doc]:
        with self._lock:
            self._fence()
            out = self.inner.find_and_modify_many(coll, query, update,
                                                  limit)
            if out:
                self._record({"op": "fam_many", "coll": coll,
                              "q": query, "u": update,
                              "lim": int(limit)})
            return out

    def remove(self, coll: str, query: Optional[Query] = None) -> int:
        with self._lock:
            self._fence()
            n = self.inner.remove(coll, query)
            if n:
                self._record({"op": "remove", "coll": coll, "q": query})
            return n

    def drop_collection(self, coll: str) -> None:
        with self._lock:
            self._fence()
            self.inner.drop_collection(coll)
            self._record({"op": "drop", "coll": coll})

    def close(self) -> None:
        self.inner.close()


class HaController:
    """One replica's HA brain: log replay/tailing, lease contention,
    self-fencing primacy, promotion and demotion.

    Roles: ``replica`` (tailing the log, answering 421), ``primary``
    (serving, heartbeating, appending), ``broken`` (the shared log
    failed validation — refuses to serve rather than diverge).
    """

    def __init__(self, ha_dir: str,
                 lease: float = DEFAULT_BOARD_LEASE,
                 fsync: bool = False,
                 holder: Optional[str] = None,
                 tail_interval: float = 0.05) -> None:
        os.makedirs(ha_dir, exist_ok=True)
        self.ha_dir = ha_dir
        self.log = MutationLog(os.path.join(ha_dir, "board.log"),
                               fsync=fsync)
        from .docstore import DirDocStore

        self.lease = BoardLease(
            _StoreCnn(DirDocStore(os.path.join(ha_dir, "lease"))),
            holder=holder, lease=lease)
        self.store = ReplicatedDocStore(
            MemoryDocStore(), self.log,
            gen_fn=lambda: int(self.lease.generation or 0),
            fence=self._check_writable)
        self.role = "replica"
        self.promotions = 0
        self.failed: Optional[BaseException] = None
        self._valid_until = 0.0          # monotonic self-fence horizon
        self._offset = 0                 # log bytes applied
        self._max_gen = 0                # generation high-water mark
        self._replayed = 0
        #: rids whose mutations were replayed without a response entry
        #: (an old primary died mid-request): refused at promotion
        self._pending_rids: Dict[str, bool] = {}
        self._handler = None             # bound by DocServer
        self._tail_interval = float(tail_interval)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- wiring -----------------------------------------------------------

    def bind_handler(self, handler) -> None:
        """The docserver's handler class: its class-level dedupe maps
        are where replayed rid answers land (duck-typed —
        ``remember_answer(rid, body)`` / ``refuse_rid(rid)``)."""
        self._handler = handler

    def start(self) -> "HaController":
        # replay whatever the log already holds BEFORE contending: a
        # restarted replica (or a fresh standby joining a live pair)
        # must be current before it can ever win the lease
        self._apply_new()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="mr-board-ha")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        if self.role == "primary":
            # clean handoff: a standby's next poll claims immediately
            try:
                self.lease.release()
            except OSError:
                pass
            self.role = "replica"
            _IS_PRIMARY.set(0)
        self.log.close()

    # -- primacy ----------------------------------------------------------

    def is_primary(self) -> bool:
        """Primacy this replica can PROVE right now: role primary AND
        the monotonic self-fence horizon (last successful renewal +
        lease period) has not passed.  A partitioned primary stops
        answering — and stops appending — before the standby's
        wait-out-the-expiry claim can succeed, so two generations
        never write concurrently (modulo clock-RATE skew; the
        generation stamps on every entry are the backstop)."""
        return (self.role == "primary"
                and time.monotonic() < self._valid_until)

    def _check_writable(self) -> None:
        if not self.is_primary():
            raise NotPrimaryError(
                f"this board replica is {self.role} "
                "(not the lease-holding primary)")

    def generation(self) -> int:
        return int(self.lease.generation or 0)

    # -- the contention / tail / heartbeat loop ---------------------------

    def _loop(self) -> None:
        beat = self.lease.lease / 4.0
        while not self._stop.is_set():
            try:
                self._loop_once(beat)
            except BoardLogCorruptError as exc:
                # from ANY replay site — tailing, a promotion drain, a
                # demote rebuild: the shared log is damaged, this
                # replica must refuse to serve rather than diverge,
                # and must say so (role + failed), never die silently
                logger.error("board log corrupt; refusing to serve: %s",
                             exc)
                self.failed = exc
                self.role = "broken"
                _IS_PRIMARY.set(0)

    def _loop_once(self, beat: float) -> None:
        if self.role == "primary":
            t0 = time.monotonic()
            try:
                owned = self.lease.heartbeat()
            except OSError:
                owned = None  # unknown: primacy decays at _valid_until
            if owned:
                self._valid_until = t0 + self.lease.lease
            elif owned is False:
                self._demote()
            self._stop.wait(beat)
        elif self.role == "replica":
            self._apply_new()
            t0 = time.monotonic()
            try:
                acquired = self.lease.try_acquire()
            except OSError:
                acquired = False  # lease store unreachable: stay replica
            if acquired:
                try:
                    self._promote(t0)
                except OSError as exc:
                    # the HA dir failed BETWEEN acquire and promote
                    # (fence-marker append / drain read — ENOSPC, NFS
                    # EIO): hand the lease back so a healthier replica
                    # (or this one, healed) claims promptly instead of
                    # the board sitting headless until expiry
                    logger.warning(
                        "promotion failed (%s); releasing the board "
                        "lease and staying replica", exc)
                    try:
                        self.lease.release()
                    except OSError:
                        pass  # expires on its own
                return
            self._stop.wait(self._tail_interval)
        else:  # broken
            self._stop.wait(1.0)

    def _promote(self, t0: float) -> None:
        # final drain: everything the dead primary managed to append is
        # ours before the first client sees us
        self._apply_new()
        # promotion FENCE MARKER: a no-op entry at our generation
        # closes the same-generation straggler window — a deposed
        # primary that passed its fence check but stalled before its
        # append either lands BEFORE this marker (the second drain
        # below applies it here, and every replay applies it — state
        # agrees) or AFTER it (generation-skipped by every replica and
        # every future replay, and never applied here — state agrees).
        # Without the marker, the bar only rises at our first real
        # mutation, and a straggler in that window would reach the
        # replicas but never this serving primary.
        self.log.append({"op": "noop", "g": self.generation(), "s": 0,
                         "holder": self.lease.holder})
        self._apply_new()
        for rid in list(self._pending_rids):
            # mutations logged, response never was: the old primary
            # died inside the request.  Whether its client saw an
            # answer is unknowable — refuse the retry loudly (the
            # dedupe plane's eviction semantics), never re-apply.
            if self._handler is not None:
                self._handler.refuse_rid(rid)
            _REFUSED_RIDS.inc()
        self._pending_rids.clear()
        self._valid_until = t0 + self.lease.lease
        self.role = "primary"
        self.promotions += 1
        _PROMOTIONS.inc()
        _IS_PRIMARY.set(1)
        _GENERATION.set(self.generation())
        _REPLAY_LAG.set(0)

    def _demote(self) -> None:
        """Definitive lease loss: fence, then REBUILD the replica from
        the log.  (If the self-fence held — it does, absent clock-rate
        pathology — we never appended a stale entry and the rebuild is
        a formality; if one slipped through, the successor skipped it
        by generation, and rebuilding from the log re-converges us to
        the successor's view.)"""
        _BOARD_FENCES.inc()
        _IS_PRIMARY.set(0)
        self.lease.generation = None
        self.role = "replica"
        with self.store._lock:
            self.store.inner = MemoryDocStore()
            self._offset = 0
            self._max_gen = 0
            self._pending_rids.clear()
        self._apply_new()

    # -- replay -----------------------------------------------------------

    def _apply_new(self) -> None:
        entries, new_offset = self.log.read_from(self._offset)
        applied = 0
        for e in entries:
            g = int(e.get("g", 0))
            if g < self._max_gen:
                # a deposed primary's straggling append: a successor
                # at a higher generation already owns the log's future
                _LOG_ENTRIES.inc(dir="skipped_stale")
                continue
            self._max_gen = g
            if e.get("op") == "resp":
                self._pending_rids.pop(e["rid"], None)
                if self._handler is not None:
                    self._handler.remember_answer(
                        e["rid"], e["body"].encode())
            else:
                apply_entry(self.store.inner, e)
                if e.get("rid"):
                    self._pending_rids[e["rid"]] = True
            self._replayed += 1
            applied += 1
        if applied:
            _LOG_ENTRIES.inc(applied, dir="replay")
        self._offset = new_offset
        if self.role != "primary":
            _REPLAY_LAG.set(max(0, self.log.size() - self._offset))

    # -- helpers ----------------------------------------------------------

    def wait_role(self, role: str, timeout: float = 30.0) -> bool:
        give_up = time.monotonic() + timeout
        while time.monotonic() < give_up:
            if self.role == role:
                return True
            time.sleep(0.02)
        return self.role == role

    def snapshot(self) -> Dict[str, Any]:
        """The /statusz ``ha`` section."""
        out: Dict[str, Any] = {
            "role": self.role,
            "generation": self.generation(),
            "holder": self.lease.holder,
            "log_bytes": self.log.size(),
            "log_appended": self.log.appended,
            "log_replayed": self._replayed,
            "promotions": self.promotions,
            # a primary appends without tailing, so its offset stops
            # moving — by definition it lags nothing
            "replay_lag_bytes": (0 if self.role == "primary" else
                                 max(0, self.log.size() - self._offset)),
        }
        try:
            doc = self.lease.peek()
        except OSError:
            doc = None
        if doc is not None:
            out["lease"] = {"holder": doc.get("holder"),
                            "generation": doc.get("generation", 0)}
        if self.failed is not None:
            out["failed"] = f"{type(self.failed).__name__}: {self.failed}"
        return out
