"""mapreduce_tpu -- a TPU-native iterative, fault-tolerant MapReduce framework.

A ground-up rebuild of the capabilities of lua-mapreduce (reference at
/root/reference, surveyed in SURVEY.md): the same user contract --
``taskfn / mapfn / partitionfn / combinerfn / reducefn / finalfn`` with
``"loop"``-style iteration, job retry/failure accounting, pluggable
intermediate storage, per-phase statistics, and distributed data-parallel
SGD -- re-designed TPU-first:

  * control plane: a host-side coordinator (in-process or shared-dir
    document store) instead of MongoDB collections (cnn.lua/task.lua);
  * data plane, general path: sorted record files + k-way merge like the
    reference's GridFS shuffle (job.lua, fs.lua, heap.lua), for arbitrary
    Python map/reduce bodies;
  * data plane, device path: one SPMD XLA program over a jax.sharding.Mesh
    -- per-shard map + local segment-reduce combine, hash partition,
    all_to_all over ICI, segmented sort/reduce (engine/);
  * training: weights resident in HBM, gradient psum over the mesh
    (models/), replacing the reference's serialize-through-GridFS SGD
    (examples/APRIL-ANN/common.lua).

Facade parity: reference mapreduce/init.lua:25-38 exports
{worker, server, utils, tuple, persistent_table, utest}.
"""

__version__ = "0.1.0"

from .utils import constants  # noqa: F401
from .utils.constants import STATUS, TASK_STATUS  # noqa: F401
from .core import interning  # noqa: F401
from .core.heap import Heap  # noqa: F401

#: the reference facade exports {worker, server, utils, tuple,
#: persistent_table, utest} (init.lua:25-38); the heavier members resolve
#: lazily so `import mapreduce_tpu` stays light (no jax import)
_LAZY = {
    "server": ".server",
    "worker": ".worker",
    "spec": ".spec",
    "storage": ".storage",
    "coord": ".coord",
    "engine": ".engine",
    "models": ".models",
    "ops": ".ops",
    "parallel": ".parallel",
    "native": ".native",
    "cli": ".cli",
    "obs": ".obs",
}

#: name parity aliases: reference `tuple` module == interning,
#: `persistent_table` lives in coord
tuple_module = interning


def __getattr__(name):
    if name == "persistent_table":
        from .coord import persistent_table as m
        return m
    if name in _LAZY:
        import importlib

        return importlib.import_module(_LAZY[name], __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
