"""Server: the planner/driver loop (reference mapreduce/server.lua).

Configures a task, plans map jobs from ``taskfn``, polls workers'
completion, plans reduce jobs from the map output files, aggregates
per-phase statistics, runs ``finalfn`` and drives the iterative ``"loop"``
cycle with crash recovery (server.lua:417-622, call stack SURVEY.md §3.1).

Differences by design: stats are computed host-side in Python (the
reference ships server-side JavaScript into mongod, server.lua:155-183);
expired RUNNING-job leases are reaped each poll (the reference only clears
stale jobs on restart, server.lua:237-245).
"""

from __future__ import annotations

import logging
import re
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from . import spec
from . import storage as storage_mod
from .coord import docstore
from .coord.connection import Connection
from .coord.job import map_results_prefix
from .coord.task import Task, make_job
from .obs import metrics as _metrics
from .obs.metrics import REGISTRY
from .obs.trace import TRACER
from .utils.constants import (
    STATUS, TASK_STATUS, DEFAULT_SLEEP, MAX_JOB_RETRIES,
    MAX_TASKFN_VALUE_SIZE)
from .utils.serialization import (
    check_serializable, serialize_record, sort_key)
from .utils.iterators import merge_iterator

logger = logging.getLogger("mapreduce_tpu.server")

TERMINAL = [int(STATUS.WRITTEN), int(STATUS.FAILED)]

# -- stats gauges: the ONE source both the persisted stats doc and the
#    /metrics exposition read, so they cannot drift apart.  Every series
#    carries the task's db label: two Server instances in one process
#    (the library supports several tasks per board) must not overwrite
#    each other's stats between publish and read-back -----------------------
_STATS_JOBS = _metrics.gauge(
    "mrtpu_stats_jobs",
    "terminal jobs in the last computed stats (labels: db, phase, "
    "state=all|failed)")
_STATS_SECONDS = _metrics.gauge(
    "mrtpu_stats_seconds",
    "per-phase timing sums from the last computed stats (labels: db, "
    "phase, field=cpu|real|cluster)")
_STATS_ITERATION = _metrics.gauge(
    "mrtpu_stats_iteration",
    "iteration the last stats doc covers (labels: db)")
_STATS_DEVICE = _metrics.gauge(
    "mrtpu_stats_device",
    "device-phase engine timings from the last run (labels: db, field)")
_PHASE_SECONDS = _metrics.histogram(
    "mrtpu_server_phase_seconds",
    "wall seconds the server spent driving one phase (labels: phase)")


def _publish_phase_stats(db: str, phase: str, d: Dict[str, Any]) -> None:
    _STATS_JOBS.set(d["count"], db=db, phase=phase, state="all")
    _STATS_JOBS.set(d["failed"], db=db, phase=phase, state="failed")
    _STATS_SECONDS.set(d["sum_cpu_time"], db=db, phase=phase, field="cpu")
    _STATS_SECONDS.set(d["sum_real_time"], db=db, phase=phase,
                       field="real")
    _STATS_SECONDS.set(d["cluster_time"], db=db, phase=phase,
                       field="cluster")


def _phase_stats_from_registry(db: str, phase: str) -> Dict[str, Any]:
    """Read one phase's stats BACK from the registry — the persisted doc
    is built from these reads, so doc and /metrics agree by construction."""
    return {
        "count": int(REGISTRY.value("mrtpu_stats_jobs", db=db,
                                    phase=phase, state="all")),
        "failed": int(REGISTRY.value("mrtpu_stats_jobs", db=db,
                                     phase=phase, state="failed")),
        "sum_cpu_time": REGISTRY.value("mrtpu_stats_seconds", db=db,
                                       phase=phase, field="cpu"),
        "sum_real_time": REGISTRY.value("mrtpu_stats_seconds", db=db,
                                        phase=phase, field="real"),
        "cluster_time": REGISTRY.value("mrtpu_stats_seconds", db=db,
                                       phase=phase, field="cluster"),
    }


class Server:
    """Reference: ``server.new(connstr, dbname, auth)`` (server.lua:614-622)."""

    def __init__(self, connstr: str, dbname: str,
                 auth: Optional[Any] = None,
                 job_lease: Optional[float] = None,
                 retry: Optional[Any] = None,
                 reclaim: Optional[Any] = None) -> None:
        self.cnn = Connection(connstr, dbname, auth, retry=retry)
        #: straggler-driven speculative re-claim (engine/autotune.
        #: SpeculativeReclaimer) — None (the default) keeps the reap
        #: loop exactly as before; the CLI surfaces attach one behind
        #: --speculative-reclaim.  Every re-claim lands in the control
        #: ledger; exactly-once rides the existing claim-guard fencing.
        self.reclaim = reclaim
        #: capacity autotuning for the device fast path (engine/
        #: autotune.AutoTuner) — None keeps the engine's hand-tuned
        #: config; the CLI surfaces attach one so a mis-tuned start
        #: converges across runs instead of re-paying retries
        self.autotune = None
        self.task = Task(self.cnn, **(
            {"job_lease": job_lease} if job_lease is not None else {}))
        self.params: Dict[str, Any] = {}
        self.configured = False
        self.finished = False
        self.poll_sleep = DEFAULT_SLEEP
        #: telemetry push cadence to the board's collector (seconds) —
        #: the driver's spans join the merged cluster timeline the same
        #: way the workers' do.  Off by default in the library (no
        #: surprise background traffic for embedders); the server CLI
        #: turns it on at 1.0s.
        self.telemetry_interval = 0.0
        # device fast path state (configure(device=True)): the mesh and
        # compiled engine live on the server instance — single-controller
        # SPMD — and never enter the task document
        self._mesh = None
        self._device_engine = None
        self._last_device_timings: Optional[Dict[str, Any]] = None

    # -- configuration (server.lua:417-460) --------------------------------

    def configure(self, params: Dict[str, Any]) -> None:
        params = dict(params)
        # a live Mesh object is config for THIS process, not task state
        self._mesh = params.pop("mesh", None)
        backend, path = storage_mod.get_storage_from(params.get("storage"))
        params["storage"] = f"{backend}:{path}"
        params["path"] = path
        spec.validate_spec(params)
        # run task/final init once, dedup by module identity
        # (server.lua:452-456)
        init_args = params.get("init_args")
        for role in ("taskfn", "finalfn"):
            spec.load_role(params[role], role).ensure_init(init_args)
        self.params = params
        self.configured = True

    # -- map planning (server.lua:249-276) ---------------------------------

    def _remove_pending_jobs(self, coll: str) -> None:
        """Clear non-terminal jobs (stale RUNNING/WAITING from a crashed
        run), keeping WRITTEN/FAILED (server.lua:237-245)."""
        self.cnn.connect().remove(
            coll, {"status": {"$nin": TERMINAL}})

    def _collect_task_pairs(self) -> List[Tuple[Any, Any]]:
        """Run taskfn and return its validated (key, value) emits
        (dup-key check + value-size cap, server.lua:256-272)."""
        taskfn = spec.load_role(self.params["taskfn"], "taskfn")
        seen: Dict[str, Any] = {}
        pairs: List[Tuple[Any, Any]] = []

        def emit(key: Any, value: Any) -> None:
            check_serializable(key)
            check_serializable(value)
            kid = str(key)
            if kid in seen:
                raise ValueError(
                    f"taskfn emitted duplicate key {key!r} "
                    "(reference dup check server.lua:256-268)")
            seen[kid] = True
            if len(repr(value)) > MAX_TASKFN_VALUE_SIZE:
                raise ValueError(
                    f"taskfn value for key {key!r} exceeds "
                    f"{MAX_TASKFN_VALUE_SIZE} bytes (utils.lua:54)")
            pairs.append((key, value))

        taskfn.fn(emit)
        return pairs

    def _prepare_map(self) -> int:
        coll = self.task.map_jobs_ns()
        self._remove_pending_jobs(coll)
        existing = {d["_id"] for d in self.cnn.connect().find(coll)}
        jobs = [make_job(k, v) for k, v in self._collect_task_pairs()
                if str(k) not in existing]  # resume: keep finished jobs
        self.task.insert_jobs(coll, jobs)
        self.task.set_task_status(TASK_STATUS.MAP)
        logger.info("map phase: %d jobs planned", len(jobs))
        return len(jobs)

    # -- completion polling (server.lua:186-234) ---------------------------

    def _poll_phase(self, coll: str, phase: str) -> None:
        """Block until every job in *coll* is WRITTEN or FAILED: reap
        expired leases, promote over-retried BROKEN jobs to FAILED, drain
        the errors channel, log progress."""
        store = self.cnn.connect()
        last_pct = -1.0
        while True:
            reaped = self.task.reap_expired(coll)
            if reaped:
                logger.warning("%s: reaped %d expired job leases", phase,
                               reaped)
            if self.reclaim is not None:
                # straggler-driven speculative re-claim (observe->act):
                # a RUNNING job held far beyond every other worker's
                # completed-job profile is broken back to claimable
                # BEFORE its lease expires; the deposed worker fences
                # at its next heartbeat/emit (the PR-1 machinery)
                self.reclaim.scan(store, coll)
            # BROKEN with repetitions >= cap -> FAILED (server.lua:192-206)
            store.update(
                coll,
                {"status": int(STATUS.BROKEN),
                 "repetitions": {"$gte": MAX_JOB_RETRIES}},
                {"$set": {"status": int(STATUS.FAILED)}}, multi=True)
            total = store.count(coll)
            done = store.count(coll, {"status": {"$in": TERMINAL}})
            errors = self.cnn.get_errors()
            if errors:
                for e in errors:
                    logger.error("worker %s error: %s", e.get("worker"),
                                 e.get("msg"))
                self.cnn.remove_errors([e["_id"] for e in errors])
            pct = 100.0 * done / max(total, 1)
            if pct != last_pct:
                logger.info("%s %.1f%% (%d/%d)", phase, pct, done, total)
                last_pct = pct
            if done >= total:
                if self.reclaim is not None:
                    # the phase drained: resolve still-pending
                    # re-claims from the final docs — scan() never
                    # runs for this coll again, and a pending ledger
                    # row must not outlive its phase
                    self.reclaim.finish(store, coll)
                return
            time.sleep(self.poll_sleep)

    # -- reduce planning (server.lua:279-329) ------------------------------

    def _prepare_reduce(self) -> int:
        storage = storage_mod.router(self.params["storage"],
                                     auth=self.cnn.auth_token(),
                                     retry=self.cnn.retry_policy)
        ns = map_results_prefix(self.params["path"])
        # group map result files by partition token P<nnnnn>
        # (server.lua:291-312)
        rx = re.compile(re.escape(ns) + r"\.(P\d+)\.M")
        parts: Dict[str, List[str]] = {}
        for name in storage.list("^" + re.escape(ns) + r"\.P\d+\.M"):
            m = rx.match(name)
            if m:
                parts.setdefault(m.group(1), []).append(name)
        coll = self.task.red_jobs_ns()
        self._remove_pending_jobs(coll)
        existing = {d["_id"] for d in self.cnn.connect().find(coll)}
        result_ns = self.task.red_results_ns()
        jobs = []
        # NOTE: no per-job "mappers" hostname list, unlike server.lua:316-323
        # — that existed for the scp pull; the reduce executor re-lists the
        # shared storage by prefix instead
        for pkey in sorted(parts):
            if pkey in existing:
                continue
            value = {"file": f"{ns}.{pkey}",
                     "result": f"{result_ns}.{pkey}"}
            jobs.append(make_job(pkey, value))
        self.task.insert_jobs(coll, jobs)
        self.task.set_task_status(TASK_STATUS.REDUCE)
        logger.info("reduce phase: %d partitions", len(jobs))
        return len(jobs)

    # -- device fast path (the unified framework, SURVEY.md §7 steps 4-5) --

    def _device_mesh(self):
        if self._mesh is None:
            from .parallel import make_mesh
            self._mesh = make_mesh()
        return self._mesh

    def _get_device_engine(self, ds: spec.DeviceSpec, mesh):
        if self._device_engine is None:
            from .engine import DeviceEngine, EngineConfig
            cfg = ds.config() if ds.config else EngineConfig()
            # the task database name is the engine's accounting label:
            # its waves/seconds/FLOPs roll up per task in the collector
            self._device_engine = DeviceEngine(mesh, ds.map_fn, cfg,
                                               task=self.cnn.dbname,
                                               autotune=self.autotune)
        return self._device_engine

    def _run_device_phase(self) -> None:
        """Fused map+shuffle+reduce on the TPU mesh: taskfn plans splits
        host-side exactly as the general path does, the module's device
        hooks turn them into one SPMD engine run, and the reduced uniques
        land in the SAME result-file contract the host reduce writes — so
        finalfn, stats, ``"loop"`` iteration and crash recovery are
        shared, not duplicated.  One job document (``__device__``) records
        the fused phase for the stats machinery; per-stage device timings
        go into it and into ``task.stats.device``
        (parity with the reference's per-phase report, server.lua:555-600).
        """
        coll = self.task.map_jobs_ns()
        # device re-runs are idempotent whole-phase: forget prior jobs
        self.cnn.connect().remove(coll, {})
        pairs = self._collect_task_pairs()
        # claim-equivalent: the server stakes the __device__ job on the
        # board.  Backdating the root span to here gives the device
        # plane the same claim -> run -> write trace the worker path
        # records, with the engine's wave spans nested under run.
        t_claim0 = time.monotonic()
        job = make_job("__device__", {"pairs": len(pairs)})
        now = docstore.now()
        job.update({"worker": "server",
                    "status": int(STATUS.RUNNING),
                    "started_time": now,
                    "lease_expires": now + self.task.job_lease})
        self.task.insert_jobs(coll, [job])
        self.task.set_task_status(TASK_STATUS.MAP)
        t_claim1 = time.monotonic()

        with TRACER.span("job", start=t_claim0, job="__device__",
                         phase="device", worker="server") as root:
            TRACER.record("claim", t_claim0, t_claim1,
                          worker="server", job="__device__")
            ds = spec.load_device(self.params["mapfn"])
            spec.load_role(self.params["mapfn"], "mapfn").ensure_init(
                self.params.get("init_args"))
            mesh = self._device_mesh()
            # monotonic for the duration fields; wall clock (docstore.now)
            # only for the started_time/written_time timestamps
            t_cpu, t_real = time.process_time(), time.monotonic()
            timings: Dict[str, Any] = {}
            with TRACER.span("run", phase="device", job="__device__"):
                chunks = ds.prepare(pairs, mesh)
                engine = self._get_device_engine(ds, mesh)
                # on_overflow="return" so the error names the MODULE
                # knob (the engine's own raise points at EngineConfig
                # generically)
                res = engine.run(chunks, timings=timings,
                                 on_overflow="return")
                if res.overflow:
                    raise RuntimeError(
                        f"device phase overflowed capacities by "
                        f"{res.overflow} rows even after retries; raise "
                        "the module's EngineConfig")
                out_pairs = list(ds.result(chunks, res))

            self.task.set_task_status(TASK_STATUS.REDUCE)
            # one key-sorted result partition file in the shared record
            # format: finalfn cannot tell which plane produced it.  Stale
            # result partitions from a crashed (possibly host-plane) run
            # are cleared first — _result_pairs merges every result.P*
            # file, so a leftover P00001 would silently blend into the
            # device output
            with TRACER.span("write", phase="device", job="__device__"):
                storage = storage_mod.router(self.params["storage"],
                                             auth=self.cnn.auth_token(),
                                             retry=self.cnn.retry_policy)
                storage.remove_many(self._result_partitions(storage))
                b = storage.builder()
                for key, values in sorted(out_pairs,
                                          key=lambda kv: sort_key(kv[0])):
                    check_serializable(key)
                    values = list(values)
                    check_serializable(values)
                    b.write_record_line(serialize_record(key, values))
                b.build(f"{self.task.red_results_ns()}.P00000")
                self.cnn.connect().update(
                    coll, {"_id": "__device__"},
                    {"$set": {"status": int(STATUS.WRITTEN),
                              "written_time": docstore.now(),
                              "cpu_time": time.process_time() - t_cpu,
                              "real_time": time.monotonic() - t_real,
                              "device_timings": timings}})
            root.args["outcome"] = "written"
        self._last_device_timings = timings
        logger.info("device phase: %d splits -> %d uniques, timings %s",
                    len(pairs), len(out_pairs), timings)

    # -- statistics (server.lua:155-183, 538-600) --------------------------

    def _phase_stats(self, coll: str) -> Dict[str, Any]:
        """Aggregate one phase's terminal job docs.

        Clock caveat: ``cpu_time``/``real_time`` are per-job durations
        measured on each worker's own monotonic clock (NTP-safe), but
        ``cluster_time`` spans DIFFERENT workers — it subtracts one
        worker's wall-clock ``started_time`` from another's
        ``written_time`` (both stamped via docstore.now), so clock skew
        between hosts leaks into it.  That is inherent to a cross-host
        makespan; treat cluster_time as approximate at skew scale.
        """
        docs = self.cnn.connect().find(coll,
                                       {"status": {"$in": TERMINAL}})
        cpu = sum(d.get("cpu_time", 0.0) for d in docs)
        real = sum(d.get("real_time", 0.0) for d in docs)
        started = [d["started_time"] for d in docs if "started_time" in d]
        written = [d["written_time"] for d in docs if "written_time" in d]
        failed = sum(1 for d in docs if d["status"] == int(STATUS.FAILED))
        return {
            "count": len(docs),
            "failed": failed,
            "sum_cpu_time": cpu,
            "sum_real_time": real,
            "cluster_time": (max(written) - min(started)
                             if started and written else 0.0),
        }

    def _compute_stats(self) -> Dict[str, Any]:
        """Aggregate job docs -> registry gauges -> persisted stats doc.

        The registry sits in the middle on purpose: the doc is built by
        READING the gauges back (_phase_stats_from_registry), so the
        /metrics exposition and the stats doc the reference persisted
        (server.lua:555-600) are the same numbers by construction.
        """
        db = self.cnn.dbname
        _publish_phase_stats(db, "map",
                             self._phase_stats(self.task.map_jobs_ns()))
        _publish_phase_stats(db, "reduce",
                             self._phase_stats(self.task.red_jobs_ns()))
        _STATS_ITERATION.set(self.task.iteration(), db=db)
        m = _phase_stats_from_registry(db, "map")
        r = _phase_stats_from_registry(db, "reduce")
        _STATS_SECONDS.set(m["cluster_time"] + r["cluster_time"],
                           db=db, phase="total", field="cluster")
        stats = {"map": m, "reduce": r,
                 "cluster_time": REGISTRY.value(
                     "mrtpu_stats_seconds", db=db, phase="total",
                     field="cluster"),
                 "iteration": int(REGISTRY.value("mrtpu_stats_iteration",
                                                 db=db))}
        if self._last_device_timings is not None:
            # per-stage device timings (upload/compute/readback/waves)
            # into the persisted stats doc — the device-path form of the
            # reference's per-phase report (server.lua:555-600) — and
            # into gauges for the live exposition
            for field, v in self._last_device_timings.items():
                if isinstance(v, (int, float)):
                    _STATS_DEVICE.set(v, db=db, field=field)
            stats["device"] = dict(self._last_device_timings)
        self.task.set_fields({"stats": stats})
        logger.info(
            "stats: map %d jobs (%d failed) cpu %.3fs cluster %.3fs | "
            "reduce %d jobs (%d failed) cpu %.3fs cluster %.3fs",
            m["count"], m["failed"], m["sum_cpu_time"], m["cluster_time"],
            r["count"], r["failed"], r["sum_cpu_time"], r["cluster_time"])
        return stats

    # -- final (server.lua:346-411) ----------------------------------------


    def _result_partitions(self, storage) -> List[str]:
        """Every result partition file for this task — the single source
        of truth for the result-file naming pattern (written by host
        reduce jobs and the device phase alike)."""
        result_ns = self.task.red_results_ns()
        return storage.list("^" + re.escape(result_ns) + r"\.P\d+$")

    def _result_pairs(self, storage) -> Iterator[Tuple[Any, List[Any]]]:
        """Merged iterator over all result partition files, globally key-
        sorted (server.lua:352-383 iterates files in sorted order; we merge
        so finalfn sees one ordered stream)."""
        names = self._result_partitions(storage)

        def records(name):
            from .utils.serialization import parse_record
            def it():
                for line in storage.open_lines(name):
                    yield parse_record(line)
            return it

        return merge_iterator([records(n) for n in names])

    def _final(self) -> Any:
        storage = storage_mod.router(self.params["storage"],
                                     auth=self.cnn.auth_token(),
                                     retry=self.cnn.retry_policy)
        finalfn = spec.load_role(self.params["finalfn"], "finalfn")
        reply = finalfn.fn(self._result_pairs(storage))
        if reply not in (True, False, None, "loop"):
            logger.warning("finalfn returned %r; expected "
                           "True/False/None/'loop' (server.lua:387-390)",
                           reply)
        result_ns = self.task.red_results_ns()
        if reply == "loop":
            # iterate: forget job boards, keep task doc (server.lua:395-398)
            logger.info("finalfn requested loop; iteration %d done",
                        self.task.iteration())
            self.cnn.connect().drop_collection(self.task.map_jobs_ns())
            self.cnn.connect().drop_collection(self.task.red_jobs_ns())
        else:
            self.task.set_task_status(TASK_STATUS.FINISHED)
            self.finished = True
        # result files are deleted unless the user asked to keep them by
        # returning False/None (server.lua:403-410)
        if reply in (True, "loop"):
            storage.remove_many(self._result_partitions(storage))
        return reply

    # -- the driver loop (server.lua:464-609) ------------------------------

    def loop(self) -> Dict[str, Any]:
        assert self.configured, "call configure() before loop()"
        # ambient token for user fns run server-side (taskfn/finalfn may
        # build their own storage handle, like worker-side map fns do);
        # scoped to this task's own endpoints and restored after — a
        # later open server on this thread must not inherit it
        from .coord.job import ambient_scope
        from .utils.httpclient import push_ambient_auth, restore_ambient_auth

        prev_auth = push_ambient_auth(
            self.cnn.auth_token(),
            ambient_scope(self.cnn, self.params.get("storage")))
        # push the driver's spans/metrics to the board's collector (when
        # the board is a networked docserver); telemetry failures can
        # never fail the run (obs/collector contract).  The pusher is
        # process-shared (acquire/release) — a driver colocated with
        # worker threads must not deliver the shared ring twice.
        from .obs.collector import acquire_pusher, release_pusher

        try:
            address = self.cnn.board_hostport()
        except Exception:
            address = None
        lease = acquire_pusher(address, self.cnn.auth_token(),
                               role=f"server:{self.cnn.dbname}",
                               interval=self.telemetry_interval)
        try:
            return self._loop_impl()
        finally:
            restore_ambient_auth(prev_auth)
            release_pusher(lease)

    def _loop_impl(self) -> Dict[str, Any]:
        it = 0
        skip_map = False
        # the execution plane is decided ONCE: params, falling back to the
        # persisted task doc on resume — so a crashed device-mode task
        # resumed by a server configured without device=True (or vice
        # versa) stays on the plane the original run recorded instead of
        # silently switching mid-task (ADVICE r3)
        device = bool(self.params.get("device"))
        # crash recovery (server.lua:468-491)
        if self.task.update():
            st = self.task.status()
            if st != TASK_STATUS.FINISHED:
                # resuming: the PERSISTED plane wins in both directions —
                # a device-configured server must not hijack a host-plane
                # task mid-run (abandoning its stored map output) any more
                # than the reverse
                doc_device = self.task.tbl.get("device")
                if doc_device is not None:
                    device = bool(doc_device)
            if st == TASK_STATUS.FINISHED:
                self.drop_collections()
            elif st == TASK_STATUS.REDUCE:
                logger.warning("resuming crashed task at REDUCE "
                               "(server.lua:475-481)")
                # restore storage decisions from the surviving task doc
                self.params["storage"] = self.task.tbl["storage"]
                self.params["path"] = self.task.tbl["path"]
                if device:
                    # the device phase is fused: re-run it whole (its
                    # map output never hits storage, so a REDUCE-state
                    # resume has nothing to reduce from)
                    it = max(self.task.iteration() - 1, 0)
                else:
                    it = self.task.iteration()
                    skip_map = True
            elif st in (TASK_STATUS.WAIT, TASK_STATUS.MAP):
                logger.warning("resuming crashed task at %s", st.value)
                self.params["storage"] = self.task.tbl["storage"]
                self.params["path"] = self.task.tbl["path"]
                it = max(self.task.iteration() - 1, 0)

        while not self.finished:
            if device:
                # unified device fast path: ONE fused SPMD phase replaces
                # map + shuffle + reduce; taskfn/finalfn/stats/loop stay
                # exactly the host machinery
                it += 1
                self.task.create_collection(TASK_STATUS.WAIT, self.params,
                                            it)
                t0 = time.monotonic()
                self._run_device_phase()
                dt = time.monotonic() - t0
                _PHASE_SECONDS.observe(dt, phase="device")
                logger.info("device map+reduce done in %.3fs", dt)
            else:
                if not skip_map:
                    it += 1
                    self.task.create_collection(TASK_STATUS.WAIT,
                                                self.params, it)
                    t0 = time.monotonic()
                    self._prepare_map()
                    self._poll_phase(self.task.map_jobs_ns(), "map")
                    dt = time.monotonic() - t0
                    _PHASE_SECONDS.observe(dt, phase="map")
                    logger.info("map done in %.3fs", dt)
                else:
                    skip_map = False
                t0 = time.monotonic()
                self._prepare_reduce()
                self._poll_phase(self.task.red_jobs_ns(), "reduce")
                dt = time.monotonic() - t0
                _PHASE_SECONDS.observe(dt, phase="reduce")
                logger.info("reduce done in %.3fs", dt)
            stats = self._compute_stats()
            self._final()
        return stats

    def drop_collections(self) -> None:
        """server_drop_collections (server.lua:331-343)."""
        store = self.cnn.connect()
        for coll in (self.task.task_ns(), self.task.map_jobs_ns(),
                     self.task.red_jobs_ns(), self.cnn.ns("errors")):
            store.drop_collection(coll)
        self.task.tbl = {}
