"""Multi-tenant scheduler: the service layer over the job board.

The reference plans ONE task per server process and polls it to
completion (server.lua:464-609); production traffic means many
concurrent tasks from many tenants sharing one board and one device
mesh (ROADMAP item 3).  This package is that service layer:

  * :mod:`.scheduler` — the board-resident task queue: per-tenant
    queues with priority + weighted-fair dequeue, admission control
    (global in-flight bound, per-tenant quotas on queued jobs/bytes),
    crash-safe state (every decision is a document mutation) and
    lease-fenced scheduler ownership (coord/lease.py patterns).  The
    docserver hosts one and speaks ``/tasks`` (submit/list/cancel,
    rid-deduped like every other board mutation).
  * :mod:`.service` — the serving processes: a :class:`TaskRunner`
    that drives admitted tasks through the unchanged ``Server``
    machinery, and :class:`ScheduledWorker` — ONE worker loop serving
    every admitted tenant's job board through the existing ``Task``
    claim machinery.
"""

from .scheduler import (  # noqa: F401
    QuotaExceededError, Scheduler, SchedulerClient, SchedulerConfig,
    SchedulerFencedError, SchedulerLease)
from .service import (  # noqa: F401
    ScheduledWorker, TaskRunner, spawn_scheduled_workers)
