"""Board-resident multi-tenant task scheduler.

Every piece of scheduler state is a DOCUMENT on the job board (the same
DocStore the task/job collections ride — mem/dir/http all work), so the
scheduler is crash-safe by construction: a restarted scheduler process
re-acquires the singleton lease and continues from the documents, the
way a restarted server resumes a crashed task (server.lua:468-491).

Collections (reserved ``__sched__`` database prefix, invisible to the
per-task board views):

  * ``__sched__.tasks`` — one doc per submitted task: tenant, target
    db, server params, priority/weight, state machine
    ``QUEUED -> ADMITTED -> RUNNING -> DONE`` (with ``CANCELLED`` /
    ``FAILED`` exits from any non-terminal state);
  * ``__sched__.tenants`` — per-tenant fair-share accounting (served
    cost, served records), ``$inc``-maintained so it survives crashes;
  * ``__sched__.state`` — the submit-sequence singleton;
  * ``__sched__.scheduler_lease`` — the fenced single-admitter
    election (:class:`SchedulerLease`, the coord/lease.py pattern at
    scheduler granularity): only the lease holder promotes QUEUED
    tasks, and a deposed scheduler's next :meth:`Scheduler.tick`
    learns it definitively and stops admitting.

Admission control on submit: per-tenant quotas on queued tasks / total
queued ``est_jobs`` / total queued ``est_bytes``, plus the two-Servers-
one-db guard — a submit naming a database that is already active
(queued/admitted/running) is REJECTED, because two Servers driving ONE
db would interleave their stats-gauge publish/read-back cycles and
persist each other's numbers (the hazard server.py's db-label comment
warns about; db labels keep *distinct* dbs apart, nothing before this
guard kept two tasks off the SAME db).

Dequeue: weighted-fair across tenants (pick the tenant with the lowest
``served_cost / weight``, charging ``max(est_jobs, 1)`` at admission),
priority + submit order within a tenant.  The global ``max_inflight``
bound is the mesh's concurrency budget.
"""

from __future__ import annotations

import itertools
import json
import threading
import uuid
from dataclasses import asdict, dataclass
from typing import Any, Dict, List, Optional

from ..coord import docstore
from ..coord.lease import TrainerLease
from ..coord.task import LeaseLostError
from ..obs import metrics as _metrics
from ..obs import slo as _slo
from ..utils.constants import STATUS

#: reserved database prefix for scheduler state on the board
SCHED_DB = "__sched__"
TASKS_COLL = f"{SCHED_DB}.tasks"
TENANTS_COLL = f"{SCHED_DB}.tenants"
STATE_COLL = f"{SCHED_DB}.state"
#: one reservation doc per ACTIVE task db — the cross-process form of
#: the one-Server-per-db guard (see Scheduler._reserve_db)
DBS_COLL = f"{SCHED_DB}.dbs"

#: a db reservation whose owning task doc is ABSENT is presumed to be a
#: submit caught between reserve and insert until this many seconds
#: old; past it the reservation is a crashed submit's leak, reclaimable
#: by a guarded steal
DB_RESERVE_GRACE = 30.0

#: the task state machine
QUEUED = "QUEUED"
ADMITTED = "ADMITTED"
RUNNING = "RUNNING"
DONE = "DONE"
CANCELLED = "CANCELLED"
FAILED = "FAILED"

#: states that hold a db active (the one-Server-per-db guard) and that
#: :meth:`Scheduler.cancel` can still reach
ACTIVE_STATES = (QUEUED, ADMITTED, RUNNING)
#: states counted against the global ``max_inflight`` bound
INFLIGHT_STATES = (ADMITTED, RUNNING)

_QUEUE_DEPTH = _metrics.gauge(
    "mrtpu_sched_queue_depth",
    "scheduler tasks by tenant and state (labels: tenant, state) — "
    "refreshed on every scheduler mutation and at /statusz scrape")
_QUEUED_WORK = _metrics.gauge(
    "mrtpu_sched_queued_work",
    "declared work waiting in a tenant's queue (labels: tenant, "
    "unit=jobs|bytes) — the quantities the per-tenant admission "
    "quotas bound")
_ADMISSION = _metrics.counter(
    "mrtpu_sched_admission_total",
    "submit admission decisions (labels: tenant, outcome=accepted|"
    "rejected, reason=-|queued_tasks|queued_jobs|queued_bytes|"
    "db_active)")
_TASK_EVENTS = _metrics.counter(
    "mrtpu_sched_tasks_total",
    "scheduler task state transitions (labels: tenant, event="
    "submitted|admitted|running|done|cancelled|failed)")
_SERVED_RECORDS = _metrics.counter(
    "mrtpu_sched_served_records_total",
    "records served per tenant, as reported by runners and engine "
    "sessions via Scheduler.note_served (labels: tenant)")
_FENCES = _metrics.counter(
    "mrtpu_sched_fences_total",
    "ticks a scheduler refused to admit because its lease was "
    "definitively lost (a successor owns admission now)")
_OLDEST_AGE = _metrics.gauge(
    "mrtpu_sched_oldest_queued_age_seconds",
    "age of each tenant's oldest QUEUED task, from the task docs' "
    "persisted submit stamps (labels: tenant) — queue DEPTH says how "
    "many wait, this says how LONG: backpressure is visible before it "
    "bites; whole-family swap on every scheduler mutation and at "
    "snapshot scrape")


class QuotaExceededError(RuntimeError):
    """A submit was refused by admission control.  ``reason`` is the
    quota that tripped (``queued_tasks`` / ``queued_jobs`` /
    ``queued_bytes`` / ``db_active``)."""

    def __init__(self, msg: str, reason: str) -> None:
        super().__init__(msg)
        self.reason = reason


class SchedulerFencedError(LeaseLostError):
    """This scheduler's admission lease is definitively gone — a
    successor scheduler owns dequeue now (strict tick() only; the
    docserver-hosted scheduler fences quietly and re-contends)."""


class _SchedCnn:
    """Minimal Connection shape over a raw DocStore for the lease
    (connect() + ns()), so the docserver can run a scheduler on the
    store it already owns with no loopback socket."""

    def __init__(self, store: docstore.DocStore) -> None:
        self._store = store

    def connect(self) -> docstore.DocStore:
        return self._store

    def ns(self, coll: str) -> str:
        return f"{SCHED_DB}.{coll}"


class SchedulerLease(TrainerLease):
    """The fenced single-admitter election: coord/lease.py's guarded
    singleton (seed-iff-absent, free-or-expired claim, ``$inc``
    generation fencing token) pointed at ``__sched__.scheduler_lease``.
    Beats/fences count in the shared trainer-lease metric family."""

    SINGLETON_ID = "scheduler"
    COLL = "scheduler_lease"

    #: schedulers tick at sub-second cadence; the lease only needs to
    #: outlive a few ticks, not an epoch + checkpoint
    DEFAULT_LEASE = 10.0

    def __init__(self, cnn, holder: Optional[str] = None,
                 lease: float = DEFAULT_LEASE) -> None:
        import socket

        super().__init__(
            cnn,
            holder=holder or (f"sched-{socket.gethostname()}-"
                              f"{uuid.uuid4().hex[:6]}"),
            lease=lease)


@dataclass(frozen=True)
class SchedulerConfig:
    """Admission-control knobs."""

    #: tasks allowed ADMITTED+RUNNING at once (the mesh/worker-pool
    #: concurrency budget)
    max_inflight: int = 2
    #: per-tenant quota: tasks waiting in the queue
    tenant_max_queued_tasks: int = 16
    #: per-tenant quota: sum of queued tasks' declared ``est_jobs``
    tenant_max_queued_jobs: int = 100_000
    #: per-tenant quota: sum of queued tasks' declared ``est_bytes``
    tenant_max_queued_bytes: int = 1 << 30
    #: retention: terminal (DONE/CANCELLED/FAILED) task docs kept on
    #: the board for the list/snapshot history; the oldest beyond this
    #: are pruned at each terminal transition — an always-on service
    #: must not grow its board (and every full-collection scan) with
    #: every task it ever served
    keep_terminal_tasks: int = 200


class Scheduler:
    """The scheduler over a DocStore (direct) — host it next to the
    store (the docserver does) or build one over any connected board.

    Thread-safety and scope: every state TRANSITION is a guarded
    ``find_and_modify`` (a raced cancel always wins over a promote),
    admission is serialized by the lease (one admitter cluster-wide),
    and the one-Server-per-db guard is a board-atomic reservation
    (:meth:`_reserve_db`) — those three hold across processes.  The
    per-tenant QUOTA sums, by contrast, are read-sum-insert under a
    process-local lock: they are resource POLICY, enforced exactly
    within one scheduler frontend; N frontends submitting for one
    tenant concurrently can transiently overshoot a quota by up to
    N-1 submits.  Route submissions through one frontend (the
    docserver's ``/tasks``) where exact quotas matter.
    """

    def __init__(self, store: docstore.DocStore,
                 config: SchedulerConfig = SchedulerConfig(),
                 lease: Optional[SchedulerLease] = None,
                 use_lease: bool = True,
                 holder: Optional[str] = None,
                 advisor: Optional[Any] = None,
                 fleet: Optional[Any] = None) -> None:
        self.store = store
        self.config = config
        self.lease = lease if lease is not None else (
            SchedulerLease(_SchedCnn(store), holder=holder)
            if use_lease else None)
        #: telemetry-informed admission (engine/autotune.
        #: AdmissionAdvisor): when session hosts register their mesh
        #: placements, an admitted task is ROUTED to the mesh whose
        #: compile ledger is warm for its program and whose HBM gauges
        #: show headroom (the pick lands in the control ledger as an
        #: admission decision).  None — the default — admits exactly
        #: as before.
        self.advisor = advisor
        #: the engine-host fleet (coord/fleet.FleetRegistry): when
        #: attached, every tick (lease-gated, so ONE sweeper
        #: cluster-wide) mirrors live hosts' heartbeat facts into the
        #: advisor, runs the failed-host recovery sweep (an expired
        #: host's streams re-home to live hosts; lazy restore makes
        #: them servable after one sweep), and an admitted task's mesh
        #: pick lands in the fleet's task->host route table.  None —
        #: the default — is the single-host scheduler bit-for-bit.
        self.fleet = fleet
        self._lock = threading.Lock()

    # -- submit (admission control) ---------------------------------------

    def _seq(self) -> int:
        self.store.update(
            STATE_COLL, {"_id": "sched", "seq": {"$exists": False}},
            {"$set": {"seq": 0}}, upsert=True)
        doc = self.store.find_and_modify(
            STATE_COLL, {"_id": "sched"}, {"$inc": {"seq": 1}})
        return int(doc["seq"])

    def _reserve_db(self, db: str, task_id: str) -> bool:
        """Atomically reserve *db* for *task_id* on the BOARD — the
        cross-process one-Server-per-db guard (a process-local lock
        cannot stop two schedulers over one shared store from both
        passing a count check).  The acquire is a guarded upsert (the
        store's duplicate-_id conflict rule refuses to overwrite an
        existing reservation, mem/dir/http alike); a reservation whose
        owning task is terminal — or absent past the grace window (a
        crashed submit) — is reclaimed by a guarded steal."""
        for _ in range(3):
            n = self.store.update(
                DBS_COLL, {"_id": db, "task": {"$exists": False}},
                {"$set": {"task": task_id,
                          "reserved_time": docstore.now()}},
                upsert=True)
            if n:
                return True
            doc = self.store.find_one(DBS_COLL, {"_id": db})
            if doc is None:
                continue  # raced a release; try the upsert again
            holder = doc.get("task")
            held = self.store.find_one(TASKS_COLL, {"_id": holder})
            if held is not None and held.get("state") in ACTIVE_STATES:
                return False  # genuinely active: refuse
            if held is None and (docstore.now()
                                 - float(doc.get("reserved_time") or 0)
                                 < DB_RESERVE_GRACE):
                # another submit is between reserve and insert: its
                # claim is valid, ours loses
                return False
            if held is not None and (docstore.now()
                                     - float(held.get("done_time") or 0)
                                     < DB_RESERVE_GRACE):
                # terminal holder whose reservation was deliberately
                # left for its DRIVER to release (cancel of a RUNNING
                # task): the driver is still draining — stealing now
                # would put two Servers on one db.  Past the grace the
                # driver is presumed dead and the leak reclaimable.
                return False
            # stale (terminal task, or a crashed submit past grace):
            # guarded steal — only wins if nobody else stole first
            if self.store.update(
                    DBS_COLL, {"_id": db, "task": holder},
                    {"$set": {"task": task_id,
                              "reserved_time": docstore.now()}}):
                return True
        return False

    def _release_db(self, doc: Dict[str, Any]) -> None:
        """Free a terminal task's db reservation (guarded: only the
        owning task's reservation is removed, never a successor's)."""
        db = doc.get("db")
        if db:
            self.store.remove(DBS_COLL,
                              {"_id": db, "task": doc["_id"]})

    def submit(self, tenant: str, db: Optional[str] = None,
               params: Optional[Dict[str, Any]] = None,
               priority: int = 0, weight: float = 1.0,
               est_jobs: int = 0, est_bytes: int = 0,
               kind: str = "server") -> Dict[str, Any]:
        """Queue one task for *tenant*; raises
        :class:`QuotaExceededError` when admission control refuses it.

        *db* is the task's database on the board (auto-generated when
        omitted); *params* the ``Server.configure`` table a runner will
        drive it with (``kind="session"`` tasks carry none — a resident
        :class:`~..engine.session.EngineSession` serves them).
        *est_jobs* / *est_bytes* are the tenant's declared cost, the
        quantities its queue quotas bound and the weighted-fair charge.
        """
        tenant = str(tenant)
        cfg = self.config
        with self._lock:
            queued = self.store.find(TASKS_COLL,
                                     {"tenant": tenant, "state": QUEUED})
            reason = None
            if len(queued) >= cfg.tenant_max_queued_tasks:
                reason = "queued_tasks"
            elif (sum(int(q.get("est_jobs") or 0) for q in queued)
                    + int(est_jobs) > cfg.tenant_max_queued_jobs):
                reason = "queued_jobs"
            elif (sum(int(q.get("est_bytes") or 0) for q in queued)
                    + int(est_bytes) > cfg.tenant_max_queued_bytes):
                reason = "queued_bytes"
            if reason is not None:
                _ADMISSION.inc(tenant=tenant, outcome="rejected",
                               reason=reason)
                raise QuotaExceededError(
                    f"submit refused for tenant {tenant!r}: {reason} "
                    f"(config {asdict(cfg)})", reason)
            seq = self._seq()
            task_id = f"{tenant}-{seq:06d}"
            db = db or f"t_{task_id}"
            # the two-Servers-one-db fix: a second task on an ACTIVE db
            # would interleave stats publish/read-back cycles and
            # persist the other task's numbers (server.py's db-label
            # comment).  The guard is an atomic BOARD-level reservation
            # (not a count check): two schedulers over one shared store
            # racing the same db resolve through the store's guarded
            # upsert, and exactly one wins.  Refused submits resubmit
            # once the holder reaches a terminal state.
            if not self._reserve_db(db, task_id):
                _ADMISSION.inc(tenant=tenant, outcome="rejected",
                               reason="db_active")
                raise QuotaExceededError(
                    f"submit refused for tenant {tenant!r}: db_active "
                    f"({db!r} is already queued/admitted/running)",
                    "db_active")
            doc = {
                "_id": task_id,
                "tenant": tenant,
                "db": db,
                "kind": kind,
                "params": params,
                "priority": int(priority),
                "weight": float(weight) if weight > 0 else 1.0,
                "est_jobs": int(est_jobs),
                "est_bytes": int(est_bytes),
                "state": QUEUED,
                "seq": seq,
                "submit_time": docstore.now(),
            }
            self.store.insert(TASKS_COLL, doc)
            # the SLO plane's monotonic submit stamp: this process can
            # now report EXACT queue-wait/first-result durations for
            # transitions it also observes (obs/slo; cross-process
            # observers fall back to the persisted submit_time)
            _slo.stamp_submit(task_id, tenant)
            _ADMISSION.inc(tenant=tenant, outcome="accepted", reason="-")
            _TASK_EVENTS.inc(tenant=tenant, event="submitted")
            self._refresh_gauges()
        return doc

    # -- dequeue (weighted-fair, priority, lease-fenced) -------------------

    def _tenant_served(self) -> Dict[str, float]:
        return {d["_id"]: float(d.get("served_cost", 0.0))
                for d in self.store.find(TENANTS_COLL)}

    def _owns_admission(self, strict: bool) -> bool:
        """Lease gate for tick(): True only with PROOF of ownership
        (acquired now, or a beat that answered owned).  A definitive
        loss fences — quietly (count + False) by default so a hosted
        scheduler just stops admitting, loudly with *strict*."""
        if self.lease is None:
            return True
        if self.lease.generation is None:
            return self.lease.try_acquire()
        try:
            owned = self.lease.heartbeat()
        except PermissionError:
            raise  # auth misconfig: retrying is no fix
        except OSError:
            return False  # ownership UNKNOWN: skip this tick, never admit
        if owned:
            return True
        self.lease.generation = None
        _FENCES.inc()
        if strict:
            raise SchedulerFencedError(
                "scheduler admission lease lost: a successor owns "
                "dequeue — this scheduler stops admitting")
        return False

    def tick(self, strict: bool = False) -> List[Dict[str, Any]]:
        """Promote QUEUED tasks into the ``max_inflight`` budget:
        weighted-fair across tenants (lowest ``served_cost/weight``
        first), priority then submit order within a tenant.  Returns
        the newly admitted task docs; empty when not the lease holder.
        """
        if not self._owns_admission(strict):
            return []
        if self.fleet is not None:
            # the fleet plane rides the SAME lease gate as admission:
            # exactly one scheduler cluster-wide mirrors host facts
            # into the advisor and sweeps for failed hosts — two
            # sweepers racing a re-home would be resolved by the
            # guarded route flips anyway, but one sweeper means one
            # auditable decision per move, not one plus a raced no-op
            try:
                self.fleet.sync_advisor(self.advisor)
                self.recovery_sweep()
            except OSError:
                pass  # board hiccup: next tick retries the sweep
        admitted: List[Dict[str, Any]] = []
        with self._lock:
            while True:
                inflight = self.store.count(
                    TASKS_COLL,
                    {"state": {"$in": list(INFLIGHT_STATES)}})
                if inflight >= self.config.max_inflight:
                    break
                queued = self.store.find(TASKS_COLL, {"state": QUEUED})
                if not queued:
                    break
                by_tenant: Dict[str, List[Dict[str, Any]]] = {}
                for q in queued:
                    by_tenant.setdefault(q["tenant"], []).append(q)
                served = self._tenant_served()

                def fair_key(t: str):
                    w = max(float(q.get("weight") or 1.0)
                            for q in by_tenant[t])
                    return (served.get(t, 0.0) / max(w, 1e-9), t)

                tenant = min(by_tenant, key=fair_key)
                cand = min(by_tenant[tenant],
                           key=lambda q: (-int(q.get("priority") or 0),
                                          int(q.get("seq") or 0)))
                gen = self.lease.generation if self.lease else 0
                doc = self.store.find_and_modify(
                    TASKS_COLL, {"_id": cand["_id"], "state": QUEUED},
                    {"$set": {"state": ADMITTED,
                              "admitted_time": docstore.now(),
                              "generation": gen}})
                if doc is None:
                    continue  # cancelled in the race; re-read the queue
                if self.advisor is not None:
                    # telemetry-informed routing: prefer a mesh whose
                    # compile ledger is warm for this task's program
                    # and whose HBM gauges show headroom — the pick
                    # (with its per-candidate evidence) is a recorded
                    # control decision; with nothing registered the
                    # task routes exactly as before
                    program = str((cand.get("params") or {})
                                  .get("program")
                                  or cand.get("kind") or "-")
                    mesh = self.advisor.choose(program, tenant=tenant,
                                               task=doc["_id"])
                    if mesh is not None:
                        self.store.update(TASKS_COLL,
                                          {"_id": doc["_id"]},
                                          {"$set": {"mesh": mesh}})
                        doc["mesh"] = mesh
                        if self.fleet is not None:
                            # the pick is also a fleet ROUTE: the
                            # task->host table is what drain and the
                            # recovery sweep re-home, and the stored
                            # program lets them score warmth later
                            self.fleet.assign(doc["_id"], mesh,
                                              program=program)
                # queue wait (submit->admitted): exact monotonic when
                # this process saw the submit, else the board's
                # persisted stamps (cross-process degradation, the
                # /statusz timestamp-comparison license)
                wait = _slo.note_admitted(doc["_id"], tenant=tenant)
                if wait is None:
                    wait = (float(doc.get("admitted_time") or 0.0)
                            - float(doc.get("submit_time") or 0.0))
                _slo.observe_queue_wait(tenant, wait)
                cost = max(float(cand.get("est_jobs") or 0), 1.0)
                self.store.update(
                    TENANTS_COLL,
                    {"_id": tenant, "served_cost": {"$exists": False}},
                    {"$set": {"served_cost": 0.0, "served_records": 0}},
                    upsert=True)
                self.store.update(TENANTS_COLL, {"_id": tenant},
                                  {"$inc": {"served_cost": cost}})
                _TASK_EVENTS.inc(tenant=tenant, event="admitted")
                admitted.append(doc)
            if admitted:
                self._refresh_gauges()
        return admitted

    # -- failed-host recovery (the fleet plane) ----------------------------

    def recovery_sweep(self) -> List[tuple]:
        """Notice expired host leases and re-home their streams: for
        every host whose lease lapsed WITHOUT a clean release, move
        each of its routed streams to the best live host (guarded
        route flips, one control-ledger ``fleet`` decision per move —
        :func:`~..coord.fleet.rehome_routes`), then reap the host doc
        under a (holder, generation) guard so the sweep fires once and
        a returning zombie fences instead of resurrecting re-homed
        streams.  The streams themselves are durable in the spill
        store and restore LAZILY on their new host's next touch, so a
        dead host's whole tenancy is servable again after this one
        sweep.  Returns the ``(task, dst_host)`` moves made."""
        if self.fleet is None:
            return []
        from ..coord import fleet as _fleet
        from ..obs import control as _control

        moves: List[tuple] = []
        now = docstore.now()
        for doc in self.fleet.expired_hosts(now):
            host_id = str(doc["_id"])
            moves.extend(_fleet.rehome_routes(
                self.fleet, host_id, reason="recovery",
                ledger=_control.LEDGER, now=now))
            if self.fleet.routes_for(host_id):
                # no live destination took them (rehome recorded the
                # refusal): leave the host EXPIRED so the next sweep
                # retries — reaping now would orphan the routes
                continue
            if self.fleet.reap(doc):
                _fleet._RECOVERIES.inc(host=host_id)
        return moves

    # -- lifecycle transitions (runner-facing) -----------------------------

    def mark_running(self, task_id: str) -> Optional[Dict[str, Any]]:
        doc = self.store.find_and_modify(
            TASKS_COLL, {"_id": task_id, "state": ADMITTED},
            {"$set": {"state": RUNNING, "started_time": docstore.now()}})
        if doc is not None:
            dt = _slo.admitted_age(task_id)
            if dt is None:
                dt = (float(doc.get("started_time") or 0.0)
                      - float(doc.get("admitted_time") or 0.0))
            _slo.observe_admit_to_running(doc["tenant"], dt)
            _TASK_EVENTS.inc(tenant=doc["tenant"], event="running")
            self._refresh_gauges()
        return doc

    def mark_done(self, task_id: str,
                  records: int = 0) -> Optional[Dict[str, Any]]:
        """RUNNING -> DONE, guarded so a raced cancel wins; *records*
        roll into the tenant's served-records accounting."""
        doc = self.store.find_and_modify(
            TASKS_COLL, {"_id": task_id, "state": RUNNING},
            {"$set": {"state": DONE, "done_time": docstore.now()}})
        if doc is not None:
            _TASK_EVENTS.inc(tenant=doc["tenant"], event="done")
            self._release_db(doc)
            _slo.drop_stamp(task_id)
            if records:
                self.note_served(doc["tenant"], records)
            self._gc_terminal()
            self._refresh_gauges()
        return doc

    def mark_failed(self, task_id: str,
                    reason: str = "") -> Optional[Dict[str, Any]]:
        doc = self.store.find_and_modify(
            TASKS_COLL,
            {"_id": task_id, "state": {"$in": [ADMITTED, RUNNING]}},
            {"$set": {"state": FAILED, "done_time": docstore.now(),
                      "reason": str(reason)[:2000]}})
        if doc is not None:
            _TASK_EVENTS.inc(tenant=doc["tenant"], event="failed")
            self._release_db(doc)
            _slo.drop_stamp(task_id)
            self._gc_terminal()
            self._refresh_gauges()
        return doc

    def _gc_terminal(self) -> None:
        """Prune the oldest terminal task docs beyond the retention cap
        (the CheckpointManager keep-N pattern for the board): tenant
        accounting survives in ``__sched__.tenants``, only the per-task
        history rows age out."""
        keep = self.config.keep_terminal_tasks
        terminal = self.store.find(
            TASKS_COLL, {"state": {"$in": [DONE, CANCELLED, FAILED]}})
        if len(terminal) <= keep:
            return
        # never prune a terminal doc that still HOLDS its db
        # reservation (a cancelled-while-RUNNING task whose driver is
        # draining): with the doc gone, _reserve_db's absent-holder
        # branch would compare against the ancient reserved_time and
        # steal the db out from under the live driver
        holding = {d.get("task") for d in self.store.find(DBS_COLL)}
        terminal.sort(key=lambda d: int(d.get("seq") or 0))
        doomed = [d["_id"] for d in terminal[:len(terminal) - keep]
                  if d["_id"] not in holding]
        if doomed:
            self.store.remove(TASKS_COLL, {"_id": {"$in": doomed}})

    def note_served(self, tenant: str, records: int) -> None:
        """Roll *records* into *tenant*'s served accounting: the live
        counter (collector/diagnose roll-ups ride it) AND the board's
        tenant doc (crash-safe, visible to every process)."""
        records = int(records)
        if records <= 0:
            return
        _SERVED_RECORDS.inc(records, tenant=str(tenant))
        self.store.update(
            TENANTS_COLL,
            {"_id": str(tenant), "served_records": {"$exists": False}},
            {"$set": {"served_cost": 0.0, "served_records": 0}},
            upsert=True)
        self.store.update(TENANTS_COLL, {"_id": str(tenant)},
                          {"$inc": {"served_records": records}})

    # -- cancel ------------------------------------------------------------

    def cancel(self, task_id: str,
               reason: str = "cancelled") -> Optional[Dict[str, Any]]:
        """Cancel a task in any non-terminal state.  A cancelled task's
        queued jobs NEVER run: its task-db singleton is forced to
        FINISHED (``Task.take_next_jobs`` answers every worker ``[]``
        from then on) and its claimable job docs are removed, so
        neither a fresh claim nor a lease-reaped BROKEN retry can
        resurrect them.

        The db reservation is released here only for QUEUED/ADMITTED
        tasks (no driver ever started).  A RUNNING task's driver is
        still inside ``Server.loop`` draining toward the FINISHED it
        just observed — releasing now would let a resubmit start a
        second Server on the same db while the first is live (the
        hazard the reservation exists for), so the DRIVER's exit path
        releases instead (TaskRunner._run_task), with the stale-
        reclaim grace as the backstop for a driverless orphan."""
        update = {"$set": {"state": CANCELLED,
                           "done_time": docstore.now(),
                           "reason": str(reason)[:2000]}}
        doc = self.store.find_and_modify(
            TASKS_COLL,
            {"_id": task_id, "state": {"$in": [QUEUED, ADMITTED]}},
            update)
        driverless = doc is not None
        if doc is None:
            doc = self.store.find_and_modify(
                TASKS_COLL, {"_id": task_id, "state": RUNNING}, update)
            if doc is None:
                return None
        _TASK_EVENTS.inc(tenant=doc["tenant"], event="cancelled")
        db = doc.get("db")
        if db:
            from ..utils.constants import TASK_STATUS

            self.store.update(
                f"{db}.task", {"_id": "unique"},
                {"$set": {"status": TASK_STATUS.FINISHED.value}})
            for coll in (f"{db}.map_jobs", f"{db}.red_jobs"):
                self.store.remove(
                    coll, {"status": {"$in": [int(STATUS.WAITING),
                                              int(STATUS.BROKEN)]}})
        if driverless:
            # released LAST (after the task-db stomp above): freeing
            # the db first would let a cancel-then-resubmit successor
            # reserve it and then eat these late FINISHED/remove writes
            self._release_db(doc)
        _slo.drop_stamp(task_id)
        self._gc_terminal()
        self._refresh_gauges()
        return doc

    # -- views -------------------------------------------------------------

    def list_tasks(self, tenant: Optional[str] = None,
                   state: Optional[str] = None) -> List[Dict[str, Any]]:
        q: Dict[str, Any] = {}
        if tenant is not None:
            q["tenant"] = str(tenant)
        if state is not None:
            q["state"] = state
        docs = self.store.find(TASKS_COLL, q or None)
        docs.sort(key=lambda d: int(d.get("seq") or 0))
        return docs

    def get(self, task_id: str) -> Optional[Dict[str, Any]]:
        return self.store.find_one(TASKS_COLL, {"_id": task_id})

    def snapshot(self) -> Dict[str, Any]:
        """The /statusz scheduler section: per-tenant queue depths and
        declared queued work, the in-flight count, fair-share and
        served-records accounting, and the admission-lease doc.  Empty
        when no task was ever submitted (the section stays off the
        page).  Refreshes the queue-depth gauges as a side effect, so
        a /statusz or /metrics scrape is always current."""
        tasks = self.store.find(TASKS_COLL)
        if not tasks:
            return {}
        tenants: Dict[str, Dict[str, Any]] = {}

        def _t(name: str) -> Dict[str, Any]:
            return tenants.setdefault(name, {
                "queued": 0, "admitted": 0, "running": 0, "done": 0,
                "cancelled": 0, "failed": 0, "queued_jobs": 0,
                "queued_bytes": 0, "served_cost": 0.0,
                "served_records": 0})

        for d in tasks:
            t = _t(d.get("tenant", "-"))
            state = str(d.get("state", QUEUED)).lower()
            if state in t:
                t[state] += 1
            if d.get("state") == QUEUED:
                t["queued_jobs"] += int(d.get("est_jobs") or 0)
                t["queued_bytes"] += int(d.get("est_bytes") or 0)
        for d in self.store.find(TENANTS_COLL):
            t = _t(d["_id"])
            t["served_cost"] = float(d.get("served_cost", 0.0))
            t["served_records"] = int(d.get("served_records", 0))
        out: Dict[str, Any] = {
            "config": asdict(self.config),
            "inflight": self.store.count(
                TASKS_COLL, {"state": {"$in": list(INFLIGHT_STATES)}}),
            "tenants": tenants,
        }
        lease_doc = self.store.find_one(
            f"{SCHED_DB}.{SchedulerLease.COLL}",
            {"_id": SchedulerLease.SINGLETON_ID})
        if lease_doc is not None:
            out["lease"] = {"holder": lease_doc.get("holder"),
                            "generation": lease_doc.get("generation", 0)}
        oldest = self._refresh_gauges(tasks=tasks)
        for t, age in oldest.items():
            if t in tenants:
                tenants[t]["oldest_queued_age_s"] = round(age, 3)
        return out

    def _refresh_gauges(self, tasks: Optional[List[Dict[str, Any]]] = None,
                        ) -> Dict[str, float]:
        """Swap the whole queue-depth / queued-work / oldest-queued-age
        families atomically (the update_board_gauges pattern): stale
        series from drained queues must not linger as lies.  Returns
        the per-tenant oldest-queued ages (the snapshot rides them)."""
        if tasks is None:
            tasks = self.store.find(TASKS_COLL)
        depth: Dict[tuple, int] = {}
        work: Dict[tuple, int] = {}
        # queue AGE alongside queue depth: oldest QUEUED submit stamp
        # per tenant, compared against the board's wall clock (persisted
        # timestamps minted through docstore.now — the same timestamp-
        # comparison license the /statusz lease view holds)
        now_wall = docstore.now()
        oldest: Dict[str, float] = {}
        for d in tasks:
            tenant = str(d.get("tenant", "-"))
            state = str(d.get("state", QUEUED))
            depth[(tenant, state)] = depth.get((tenant, state), 0) + 1
            if state == QUEUED:
                work[(tenant, "jobs")] = (work.get((tenant, "jobs"), 0)
                                          + int(d.get("est_jobs") or 0))
                work[(tenant, "bytes")] = (work.get((tenant, "bytes"), 0)
                                           + int(d.get("est_bytes") or 0))
                age = max(0.0, now_wall
                          - float(d.get("submit_time") or now_wall))
                oldest[tenant] = max(oldest.get(tenant, 0.0), age)
        _QUEUE_DEPTH.replace(
            [({"tenant": t, "state": s}, n)
             for (t, s), n in sorted(depth.items())])
        _QUEUED_WORK.replace(
            [({"tenant": t, "unit": u}, n)
             for (t, u), n in sorted(work.items())])
        _OLDEST_AGE.replace(
            [({"tenant": t}, round(a, 3))
             for t, a in sorted(oldest.items())])
        return oldest

    def release(self) -> None:
        """Clean handoff of the admission lease (a successor's
        try_acquire succeeds immediately)."""
        if self.lease is not None and self.lease.generation is not None:
            try:
                self.lease.release()
            except OSError:
                pass  # board unreachable: the lease expires on its own


# -- the /tasks HTTP client ---------------------------------------------------


class SchedulerClient:
    """Client for the docserver's ``/tasks`` surface (the submit/list/
    cancel CLI rides it).  Mutations carry ``SESSION:SEQ`` request ids
    and are deduped server-side exactly like board RPCs — a retried
    submit cannot enqueue twice.  Accepts the multi-endpoint HA board
    form (``HOST:PORT,HOST:PORT``): a dead or standby replica rotates
    under the one rid, and the replicated dedupe table keeps the
    failed-over re-send exactly-once.

    Backpressure contract: the server answers quota rejections with
    HTTP 429 + the typed body.  This client strips 429 from its retry
    statuses ON PURPOSE — an admission rejection is an ANSWER
    (:class:`QuotaExceededError` with its reason), not a transient to
    hammer through."""

    def __init__(self, address: str, auth_token: Optional[str] = None,
                 retry=None) -> None:
        import dataclasses

        from ..utils.httpclient import (
            DEFAULT_RETRY_POLICY, FailoverClient)

        policy = retry if retry is not None else DEFAULT_RETRY_POLICY
        policy = dataclasses.replace(
            policy,
            retry_statuses=frozenset(policy.retry_statuses) - {429})
        self._client = FailoverClient(
            address, what="scheduler", auth_token=auth_token,
            retry=policy)
        self._rid_session = uuid.uuid4().hex
        self._rid_seq = itertools.count(1)
        self._lock = threading.Lock()

    def _call(self, op: str, **fields: Any) -> Any:
        payload: Dict[str, Any] = {"op": op, **fields}
        with self._lock:
            payload["rid"] = (f"{self._rid_session}:"
                              f"{next(self._rid_seq)}")
            status, raw = self._client.request(
                "POST", "/tasks", body=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
        if status == 401:
            raise PermissionError(
                f"/tasks {op!r}: auth rejected (set $MAPREDUCE_TPU_AUTH "
                "or pass auth)")
        if status == 404:
            raise IOError(
                "/tasks: this docserver predates the scheduler surface")
        if status not in (200, 429):
            # 429 carries the typed quota rejection in its body — fall
            # through to the typed-error dispatch below
            raise IOError(f"/tasks {op!r}: HTTP {status}")
        reply = json.loads(raw)
        if not reply.get("ok"):
            exc_type = {"QuotaExceededError": None,
                        "ValueError": ValueError,
                        "KeyError": KeyError,
                        "PermissionError": PermissionError,
                        }.get(reply.get("type"), IOError)
            if exc_type is None:
                raise QuotaExceededError(reply.get("error", "rejected"),
                                         reply.get("reason", "-"))
            raise exc_type(reply.get("error", "/tasks call failed"))
        return reply.get("result")

    def submit(self, tenant: str, **kw: Any) -> Dict[str, Any]:
        return self._call("submit", tenant=tenant, **kw)

    def cancel(self, task_id: str,
               reason: str = "cancelled") -> Optional[Dict[str, Any]]:
        return self._call("cancel", task_id=task_id, reason=reason)

    def tick(self) -> List[Dict[str, Any]]:
        return self._call("tick")

    def list(self) -> Dict[str, Any]:
        """GET /tasks: every task doc plus the scheduler snapshot."""
        status, raw = self._client.request("GET", "/tasks")
        if status == 401:
            raise PermissionError("/tasks: auth rejected")
        if status != 200:
            raise IOError(f"/tasks: HTTP {status}")
        return json.loads(raw)

    def close(self) -> None:
        self._client.close()
