"""The serving processes of the always-on service.

Two loops close the submit -> result path over the scheduler's board
state (sched/scheduler.py):

  * :class:`TaskRunner` — the driver pool: ticks the scheduler
    (admission, lease-fenced) and drives every ADMITTED ``server``-kind
    task through the UNCHANGED ``Server`` machinery, one thread per
    in-flight task.  Phases, stats, crash recovery and ``"loop"``
    iteration are all the existing Server.loop — the runner only maps
    scheduler states onto it (ADMITTED -> RUNNING -> DONE/FAILED,
    guarded so a raced cancel wins).
  * :class:`ScheduledWorker` — ONE worker loop serving N tenants: it
    polls the scheduler's admitted/running set and claims each active
    task's jobs through the existing per-db ``Task`` machinery
    (batched claims, heartbeats, per-claim fencing — worker.py
    unchanged), cycling across tasks so no tenant starves while
    another has claimable jobs.  A cancelled task vanishes from the
    active set AND its task doc reads FINISHED, so its queued jobs are
    unclaimable from either direction.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional

from ..coord import docstore
from ..obs import slo as _slo
from ..obs.metrics import REGISTRY
from ..utils.constants import STATUS
from ..worker import Worker
from .scheduler import ADMITTED, INFLIGHT_STATES, RUNNING, Scheduler
from .scheduler import TASKS_COLL

logger = logging.getLogger("mapreduce_tpu.sched")


class TaskRunner:
    """Drive admitted tasks to completion through ``Server``.

    The runner owns admission: its poll loop calls
    :meth:`Scheduler.tick` (a no-op unless this process holds — or can
    take — the scheduler lease) and then starts one driver thread per
    newly admitted ``server`` task, up to the scheduler's own
    ``max_inflight`` bound.  Session-kind tasks are left to whatever
    :class:`~..engine.session.EngineSession` host claimed them.
    """

    def __init__(self, connstr: str, scheduler: Scheduler,
                 auth: Optional[Any] = None, retry: Optional[Any] = None,
                 job_lease: Optional[float] = None,
                 poll: float = 0.05) -> None:
        self.connstr = connstr
        self.scheduler = scheduler
        self.auth = auth
        self.retry = retry
        self.job_lease = job_lease
        self.poll = poll
        self._threads: Dict[str, threading.Thread] = {}
        self._stop = threading.Event()
        self._main: Optional[threading.Thread] = None
        #: terminal failure (auth misconfig) that stopped the loop —
        #: embedders/cmd_runner surface it instead of spinning forever
        self.failed: Optional[BaseException] = None

    def start(self) -> "TaskRunner":
        self._main = threading.Thread(target=self._loop, daemon=True,
                                      name="mr-sched-runner")
        self._main.start()
        return self

    def stop(self, timeout: float = 30.0) -> None:
        self._stop.set()
        if self._main is not None:
            self._main.join(timeout=timeout)
        for t in list(self._threads.values()):
            t.join(timeout=timeout)
        self.scheduler.release()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.scheduler.tick()
                for doc in self.scheduler.list_tasks(state=ADMITTED):
                    if doc.get("kind") != "server":
                        continue  # session tasks are served in-place
                    tid = doc["_id"]
                    if tid in self._threads:
                        continue
                    t = threading.Thread(target=self._run_task,
                                         args=(doc,), daemon=True,
                                         name=f"mr-sched-{tid}")
                    self._threads[tid] = t
                    t.start()
                # reap finished driver threads so re-submits of a freed
                # db get a fresh slot
                for tid in [k for k, t in self._threads.items()
                            if not t.is_alive()]:
                    self._threads.pop(tid, None)
            except PermissionError as exc:
                # auth misconfig never heals on its own: stop the loop
                # loudly instead of retrying at poll cadence forever
                logger.error("runner auth rejected by the board (%s); "
                             "stopping", exc)
                self.failed = exc
                self._stop.set()
                return
            except OSError as exc:
                logger.warning("scheduler poll failed (%s); backing off",
                               exc)
            self._stop.wait(self.poll)

    def _served_records(self, db: str) -> int:
        """Records this task's jobs wrote, from the per-task accounting
        counters (coord/job.py increments them at write time) — the
        local-process view; cross-process rows roll up on /clusterz."""
        n = REGISTRY.sum("mrtpu_task_records_total", task=db,
                         phase="map")
        if not n:
            n = REGISTRY.sum("mrtpu_task_records_total", task=db)
        return int(n)

    def _watch_first_result(self, doc: Dict[str, Any]) -> None:
        """The SLO plane's running→first-job-written stamp: poll the
        task db for its first WRITTEN job (one cheap count per poll
        tick, bounded by the task's lifetime) and observe the tenant's
        submit→first-result latency — exact monotonic when this process
        saw the submit, else the board's persisted submit stamp."""
        tid, db, tenant = doc["_id"], doc["db"], doc["tenant"]
        store = self.scheduler.store
        written_q = {"status": int(STATUS.WRITTEN)}
        # a REUSED db (prior run DONE, resubmitted) still carries the
        # previous run's WRITTEN job docs until the new Server's loop
        # drops the collections: those must not read as an instant
        # first result.  The first poll's count is the stale baseline;
        # only a count that MOVED (the drop zeroes it, a fresh write
        # raises it) is this run's first result.
        baseline = None
        while not self._stop.is_set():
            try:
                done = 0
                for coll in (f"{db}.map_jobs", f"{db}.red_jobs"):
                    done += store.count(coll, written_q)
                if baseline is None:
                    baseline = done
                elif done == 0:
                    baseline = 0  # the new run dropped the stale docs
                if done and done != baseline:
                    _slo.observe_first_result(
                        tid, tenant,
                        fallback_s=(docstore.now()
                                    - float(doc.get("submit_time")
                                            or docstore.now())))
                    return
                task = self.scheduler.get(tid)
                if task is None or task.get("state") != RUNNING:
                    return  # terminal before any job was written
            except PermissionError:
                # auth misconfig never heals on its own (the _loop
                # carve-out): exit rather than spin at poll cadence
                # forever — the SLO observation is telemetry, the
                # runner's own loop surfaces the failure
                logger.debug("first-result watcher for %s: auth "
                             "rejected; giving up", tid)
                return
            except OSError:
                pass  # board blip: telemetry degrades, never raises
            self._stop.wait(max(self.poll, 0.02))

    def _run_task(self, doc: Dict[str, Any]) -> None:
        from ..server import Server  # late: keep the module jax-free

        tid = doc["_id"]
        if self.scheduler.mark_running(tid) is None:
            return  # a cancel won the race: never start the driver
        threading.Thread(target=self._watch_first_result, args=(doc,),
                         daemon=True,
                         name=f"mr-slo-watch-{tid}").start()
        try:
            kw: Dict[str, Any] = {}
            if self.job_lease is not None:
                kw["job_lease"] = self.job_lease
            server = Server(self.connstr, doc["db"], auth=self.auth,
                            retry=self.retry, **kw)
            server.configure(dict(doc.get("params") or {}))
            server.loop()
        except Exception as exc:
            # the shield: one tenant's broken task must not take the
            # runner (or any other tenant) down with it
            logger.exception("task %s failed", tid)
            if self.scheduler.mark_failed(
                    tid, reason=f"{type(exc).__name__}: {exc}") is None:
                # a cancel won while the driver ran: the db reservation
                # was deliberately left for THIS exit path to release
                self.scheduler._release_db(doc)
            return
        if self.scheduler.mark_done(
                tid, records=self._served_records(doc["db"])) is None:
            self.scheduler._release_db(doc)


class ScheduledWorker:
    """One worker loop claiming across every admitted tenant's task.

    Wraps the existing :class:`~..worker.Worker` per task db (claims,
    heartbeats, lease fencing, batched claim-ahead all unchanged) and
    cycles over the scheduler's active set in submit order, giving each
    task a bounded slice (``Worker._execute_task`` with a small
    ``max_iter`` returns once the task goes idle), so one pool drains N
    tenants' boards without any tenant monopolising it.
    """

    def __init__(self, connstr: str, auth: Optional[Any] = None,
                 name: Optional[str] = None, retry: Optional[Any] = None,
                 conf: Optional[Dict[str, Any]] = None,
                 job_lease: Optional[float] = None,
                 poll: float = 0.05,
                 idle_backoff: float = 0.5) -> None:
        self.connstr = connstr
        self.auth = auth
        self.retry = retry
        self.name = name or f"sw-{id(self):x}"
        self.job_lease = job_lease
        self.poll = poll
        #: a task whose last slice found no work is skipped for this
        #: long: an always-on pool over N mostly-idle tasks must not
        #: burn a claim RPC + a heartbeat-thread spawn per task per
        #: poll tick forever
        self.idle_backoff = idle_backoff
        self._idle_until: Dict[str, float] = {}
        #: per-slice worker knobs: a small max_iter bounds how long an
        #: idle task holds the loop before the next tenant's turn
        self.conf = {"max_iter": 2, "max_sleep": 0.1, **(conf or {})}
        self._workers: Dict[str, Worker] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._store: Optional[docstore.DocStore] = None
        #: terminal failure (auth misconfig) that stopped this worker —
        #: observable (cmd_runner watches it); the loop still runs its
        #: held-claim release on the way out
        self.failed: Optional[BaseException] = None

    def start(self) -> "ScheduledWorker":
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name=f"mr-{self.name}")
        self._thread.start()
        return self

    def stop(self, timeout: float = 30.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    def _active_tasks(self) -> List[Dict[str, Any]]:
        if self._store is None:
            self._store = docstore.connect(self.connstr, auth=self.auth,
                                           retry=self.retry)
        docs = self._store.find(
            TASKS_COLL, {"state": {"$in": list(INFLIGHT_STATES)},
                         "kind": "server"})
        docs.sort(key=lambda d: int(d.get("seq") or 0))
        return docs

    def _worker_for(self, db: str) -> Worker:
        w = self._workers.get(db)
        if w is None:
            w = Worker(self.connstr, db, auth=self.auth,
                       name=f"{self.name}:{db}", retry=self.retry)
            w.configure(self.conf)
            if self.job_lease is not None:
                w.task.job_lease = self.job_lease
            self._workers[db] = w
        return w

    def run(self) -> None:
        """The pool loop: serve every active task a slice, sleep when
        the whole service is idle.  Board unreachability is an idle
        cycle, not a death — the claim loop inside Worker already
        shields per-RPC faults, this shields the scheduler poll."""
        while not self._stop.is_set():
            try:
                active = self._active_tasks()
            except PermissionError as exc:
                # auth misconfig: retrying is no fix.  Stop OBSERVABLY
                # (failed flag, not a raise that dies silently in a
                # daemon thread) and fall through to the held-claim
                # release below so another worker picks the jobs up now
                logger.error("%s: board auth rejected (%s); stopping",
                             self.name, exc)
                self.failed = exc
                self._stop.set()
                break
            except OSError as exc:
                logger.warning("%s: scheduler board unreachable (%s)",
                               self.name, exc)
                self._stop.wait(max(self.poll, 0.2))
                continue
            # forget workers whose task left the active set, EVERY
            # cycle: a continuously busy service must not accumulate
            # one handle (socket + claim state) per tenant db ever seen
            active_dbs = {d["db"] for d in active}
            for db in [d for d in self._workers if d not in active_dbs]:
                self._workers.pop(db, None)
                self._idle_until.pop(db, None)
            if not active:
                self._stop.wait(self.poll)
                continue
            sliced = False
            for doc in active:
                if self._stop.is_set():
                    break
                db = doc["db"]
                if time.monotonic() < self._idle_until.get(db, 0.0):
                    continue  # idle backoff: nothing claimable last time
                sliced = True
                try:
                    worked = self._worker_for(db)._execute_task()
                    self._idle_until[db] = (
                        0.0 if worked
                        else time.monotonic() + self.idle_backoff)
                except PermissionError as exc:
                    logger.error("%s: auth rejected mid-slice (%s); "
                                 "stopping", self.name, exc)
                    self.failed = exc
                    self._stop.set()
                    break
                except Exception:
                    logger.exception("%s: slice on task %s failed",
                                     self.name, doc["_id"])
            if not sliced:
                # every active task is in idle backoff: pace the poll
                # instead of spinning the active-set query hot
                self._stop.wait(self.poll)
        # release anything still held so the next worker claims it now
        for w in self._workers.values():
            try:
                with w._held_lock:
                    held = list(w._held.values())
                for coll, job_tbl, _fence in held:
                    w.task.release_jobs(coll, [job_tbl])
            except Exception:
                logger.debug("%s: exit release failed", self.name,
                             exc_info=True)


def spawn_scheduled_workers(connstr: str, n: int,
                            auth: Optional[Any] = None,
                            retry: Optional[Any] = None,
                            conf: Optional[Dict[str, Any]] = None,
                            job_lease: Optional[float] = None,
                            name_prefix: str = "sw",
                            ) -> List[ScheduledWorker]:
    """Start *n* cross-tenant workers as daemon threads (the scheduled
    analogue of :func:`~..worker.spawn_worker_threads`)."""
    pool = []
    for i in range(n):
        w = ScheduledWorker(connstr, auth=auth, retry=retry, conf=conf,
                            job_lease=job_lease,
                            name=f"{name_prefix}-{i}")
        w.start()
        pool.append(w)
    return pool


def wait_for_state(scheduler: Scheduler, task_id: str, states,
                   timeout: float = 60.0, poll: float = 0.05,
                   ) -> Dict[str, Any]:
    """Block until *task_id* reaches one of *states*; the submit-and-
    wait convenience the CLI and tests use."""
    states = {states} if isinstance(states, str) else set(states)
    give_up = time.monotonic() + timeout
    while True:
        doc = scheduler.get(task_id)
        if doc is not None and doc.get("state") in states:
            return doc
        if time.monotonic() >= give_up:
            raise TimeoutError(
                f"task {task_id} not in {sorted(states)} within "
                f"{timeout}s (currently "
                f"{doc.get('state') if doc else 'absent'})")
        time.sleep(poll)
