"""The shuffle: hash-partition + capacity-bounded ``all_to_all``.

This is the device-native replacement for the reference's entire shuffle
machinery — partitionfn hashing on the host (partitionfn.lua:2-15),
per-partition intermediate *files* (job.lua:196-221), reduce jobs pulling
those files over GridFS/NFS/scp (fs.lua:141-181), and the k-way merge
(utils.lua:206-271).  Here a record's partition is ``key_hi mod P``; every
device packs its records into a ``[P, C, lanes]`` send buffer and one
``lax.all_to_all`` over the mesh axis moves partition *p*'s records to
device *p* over ICI, inside the compiled program.

Static shapes on a dynamic problem (SURVEY.md §7 hard part (a)): the
per-destination capacity ``C`` is fixed; rows beyond it are counted in
``overflow`` (never silently lost — callers check and re-run with a larger
C).  Packing is scatter-based (O(N)), not sort-based.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


class Exchanged(NamedTuple):
    keys: jax.Array      # [(A+)P*C, 2] uint32 — records received here
    values: jax.Array    # [(A+)P*C, ...]
    payload: jax.Array   # [(A+)P*C, Q] int32
    valid: jax.Array     # [(A+)P*C] bool
    overflow: jax.Array  # [] int32 — rows dropped on the SEND side here
    max_count: jax.Array  # [] int32 — largest per-destination row count
    #                       BEFORE capping (what capacity SHOULD have been)
    counts: jax.Array    # [P] int32 — valid rows this device ROUTED to
    #                       each destination, before capacity capping:
    #                       THIS device's row of the src×dst exchange
    #                       traffic matrix (obs/comms)


def partition_exchange(keys: jax.Array, values: jax.Array,
                       payload: jax.Array, valid: jax.Array,
                       axis_name: str, capacity: int,
                       carry: Optional[Tuple] = None,
                       pmap: Optional[jax.Array] = None,
                       impl: str = "lax") -> Exchanged:
    """Exchange records so device ``p`` ends up with every record whose
    ``key_hi % P == p``.  Must run inside ``shard_map`` over *axis_name*.

    ``capacity`` bounds rows per (source, destination) pair.

    ``carry`` is the accumulator-carrying spec for the fused wave fold:
    an optional ``(keys [A,2], values [A,...], payload [A,Q],
    valid [A])`` of rows ALREADY belonging to this device's partition
    (the running per-partition uniques of earlier waves).  They are
    prepended to the received rows — before, not after, so a stable
    downstream sort keeps accumulator rows ahead of same-key wave rows
    and the fold order stays ``acc ⊕ wave`` — letting the caller's
    merge reduce accumulator + fresh records in ONE pass with no extra
    dispatch or concatenate allocation outside the compiled program.

    ``pmap`` (the skew-control hook, engine/autotune.py) generalizes
    the partition function to an indirection table: a replicated
    ``[B] int32`` array mapping hash bucket ``key_hi % B`` to its
    destination partition.  The identity table
    (``pmap[b] = b % P``, with ``P | B``) reproduces ``key_hi % P``
    EXACTLY — ``(k % B) % P == k % P`` whenever P divides B — so a run
    that never rebalances is bit-identical to ``pmap=None``; a
    rebalanced table routes each hot bucket wherever the controller
    binned it, inside the same compiled program (the table is an
    input, not a constant — no recompile per rebalance).

    ``impl`` picks the routing-plan formulation: ``"lax"`` (default)
    is the one-hot cumsum below; ``"radix"`` fuses the plan into the
    radix kernel program (ops/radix_sort.radix_partition_plan) — ONE
    destination-digit histogram kernel yields both the scatter ranks
    and the ``counts`` traffic-matrix row, deleting the separate
    count pass.  Both are bit-identical in every output field (the
    golden suite pins it); buffer packing, the collective, and the
    carry prepend are shared verbatim.
    """
    if impl not in ("lax", "radix"):
        raise ValueError(f"exchange impl must be 'lax' or 'radix', "
                         f"got {impl!r}")
    P = jax.lax.psum(1, axis_name)
    n = keys.shape[0]
    if pmap is None:
        dest = (keys[:, 0] % jnp.uint32(P)).astype(jnp.int32)
    else:
        B = pmap.shape[0]
        bucket = (keys[:, 0] % jnp.uint32(B)).astype(jnp.int32)
        dest = pmap[bucket].astype(jnp.int32)
    dest = jnp.where(valid, dest, P)  # invalid -> out-of-range, dropped

    # rank of each row within its destination bucket; counts[d] = rows
    # wanted per destination (this device's traffic-matrix row)
    if impl == "radix":
        # fused plan: one histogram kernel pass feeds both outputs
        from ..ops.radix_sort import radix_partition_plan
        rank, counts = radix_partition_plan(dest, P)
    else:
        # one-hot cumsum: rank[i] = #{j < i : dest[j] == dest[i]}
        # (O(N*P) elementwise — P is the mesh size, small; avoids a sort)
        onehot = (dest[:, None] == jnp.arange(P)[None, :]).astype(jnp.int32)
        rank = jnp.take_along_axis(
            jnp.cumsum(onehot, axis=0) - 1,
            jnp.clip(dest, 0, P - 1)[:, None], axis=1)[:, 0]
        counts = onehot.sum(axis=0)
    overflow = jnp.maximum(counts - capacity, 0).sum()

    def scatter(arr, fill=0):
        buf = jnp.full((P, capacity) + arr.shape[1:], fill, dtype=arr.dtype)
        return buf.at[dest, rank].set(arr, mode="drop")

    send_keys = scatter(keys)
    send_vals = scatter(values)
    send_pay = scatter(payload)
    send_live = scatter(valid.astype(jnp.int32))

    # one collective moves the whole shuffle over ICI: slot [d] of the
    # send buffer goes to device d; slot [s] of the result came from s
    recv_keys = jax.lax.all_to_all(send_keys, axis_name, 0, 0, tiled=False)
    recv_vals = jax.lax.all_to_all(send_vals, axis_name, 0, 0, tiled=False)
    recv_pay = jax.lax.all_to_all(send_pay, axis_name, 0, 0, tiled=False)
    recv_live = jax.lax.all_to_all(send_live, axis_name, 0, 0, tiled=False)

    flat = lambda a: a.reshape((P * capacity,) + a.shape[2:])
    out_keys = flat(recv_keys)
    out_vals = flat(recv_vals)
    out_pay = flat(recv_pay)
    out_valid = flat(recv_live) == 1
    if carry is not None:
        ck, cv, cp, cvalid = carry
        out_keys = jnp.concatenate([ck, out_keys], axis=0)
        out_vals = jnp.concatenate([cv, out_vals], axis=0)
        out_pay = jnp.concatenate([cp, out_pay], axis=0)
        out_valid = jnp.concatenate([cvalid, out_valid], axis=0)
    return Exchanged(
        keys=out_keys,
        values=out_vals,
        payload=out_pay,
        valid=out_valid,
        overflow=overflow,
        max_count=counts.max().astype(jnp.int32),
        counts=counts.astype(jnp.int32),
    )
