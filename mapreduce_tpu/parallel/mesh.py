"""Device-mesh construction helpers.

One place decides how devices become a named :class:`jax.sharding.Mesh`.
Axis conventions across the framework:

  * ``"data"`` — the map/shuffle data-parallel axis (shards of input,
    one reduce partition per device position);
  * ``"model"`` — reserved for tensor-parallel model state in the
    training path (models/), size 1 unless requested.

On multi-host slices, callers pass ``jax.devices()`` (all global devices)
and the mesh spans hosts; ICI carries the collectives within a slice and
DCN across slices — axis order puts ``"data"`` innermost so its
collectives ride ICI.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh


def make_mesh(n_data: Optional[int] = None, n_model: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a ``(model, data)`` mesh from the first ``n_model*n_data``
    devices (default: all).  ``data`` is the fastest-varying (innermost)
    axis so neighbouring mesh positions are ICI neighbours."""
    devices = list(devices if devices is not None else jax.devices())
    if n_data is None:
        n_data = len(devices) // n_model
    need = n_model * n_data
    if need > len(devices):
        raise ValueError(
            f"mesh wants {need} devices, only {len(devices)} available")
    grid = np.array(devices[:need]).reshape(n_model, n_data)
    return Mesh(grid, ("model", "data"))


def data_axis_size(mesh: Mesh) -> int:
    """GLOBAL size of the data axis (spans all hosts on a multi-host mesh)."""
    return mesh.shape["data"]


# -- topology model: link classes + per-class peak bandwidth ------------------
#
# The comms observability layer (obs/comms) rolls the exchange traffic
# matrix up by the KIND of wire each (src, dst) device pair talks over.
# Device objects carry enough identity to classify honestly:
#
#   * ``self`` — src is dst: the all_to_all's diagonal never leaves the
#     chip (an HBM copy, not interconnect traffic);
#   * ``ici``  — different chips inside one slice: the inter-chip
#     interconnect (the wire the ROADMAP's 2-D mesh keeps the shuffle
#     on);
#   * ``dcn``  — chips in different slices (``slice_index`` differs):
#     the data-center network between slices;
#   * ``host`` — no accelerator interconnect at all (CPU devices, the
#     tier-1 test mesh): bytes move through host memory.
#
# Like obs/profile's FLOPs peaks, the bandwidth numbers are datasheet-
# order denominators for a roofline ratio, not measurements — the table
# says so via ``peak_source`` and every figure derived from it is
# labelled ``source="analytic"``.

#: link classes, in locality order
LINK_CLASSES: Tuple[str, ...] = ("self", "ici", "dcn", "host")

#: default per-link-class peak bandwidth (bytes/s per device pair):
#: self = HBM copy bandwidth order, ici = one v5e ICI link direction,
#: dcn = ~100 Gb/s NIC share, host = host-memory/PCIe order.
_DEFAULT_LINK_PEAKS: Dict[str, float] = {
    "self": 819e9,   # on-chip: HBM bandwidth order (v5e datasheet)
    "ici": 45e9,     # per-link ICI, one direction (v5e: ~1.6Tb/s over
    #                  4 links -> ~45GB/s per link-direction)
    "dcn": 12.5e9,   # 100 Gb/s data-center NIC
    "host": 10e9,    # host-memory staging / PCIe order
}

#: env override names, checked by :func:`link_peaks`
_LINK_PEAK_ENV = {
    cls: f"MAPREDUCE_TPU_PEAK_{cls.upper()}_BYTES_PER_S"
    for cls in LINK_CLASSES}


def link_peaks() -> Dict[str, Any]:
    """The per-link-class peak-bandwidth table (bytes/s), each class
    individually overridable via ``MAPREDUCE_TPU_PEAK_<CLASS>_BYTES_PER_S``;
    ``peak_source`` records which figures came from the environment so
    the numbers stay honest about their provenance."""
    out: Dict[str, Any] = {}
    overridden: List[str] = []
    for cls in LINK_CLASSES:
        env = os.environ.get(_LINK_PEAK_ENV[cls])
        if env:
            out[cls] = float(env)
            overridden.append(cls)
        else:
            out[cls] = _DEFAULT_LINK_PEAKS[cls]
    out["peak_source"] = ("env:" + ",".join(overridden) if overridden
                          else "datasheet")
    return out


def link_class(src: Any, dst: Any) -> str:
    """Classify the wire between two devices (jax Device objects or
    anything with ``id``/``platform``/``slice_index`` attrs) as
    ``self`` / ``ici`` / ``dcn`` / ``host``."""
    if src is dst or getattr(src, "id", None) == getattr(dst, "id", object()):
        return "self"
    platform = str(getattr(src, "platform", "") or "").lower()
    if platform == "cpu":
        return "host"  # no accelerator interconnect: host-memory copies
    s_slice = getattr(src, "slice_index", None)
    d_slice = getattr(dst, "slice_index", None)
    if s_slice is not None and d_slice is not None and s_slice != d_slice:
        return "dcn"
    return "ici"


def device_link_matrix(devices: Sequence[Any]) -> List[List[str]]:
    """``[n, n]`` link-class names for every (src, dst) device pair, in
    the data-axis order the exchange traffic matrix uses."""
    return [[link_class(s, d) for d in devices] for s in devices]
