"""Device-mesh construction helpers.

One place decides how devices become a named :class:`jax.sharding.Mesh`.
Axis conventions across the framework:

  * ``"data"`` — the map/shuffle data-parallel axis (shards of input,
    one reduce partition per device position);
  * ``"model"`` — reserved for tensor-parallel model state in the
    training path (models/), size 1 unless requested.

On multi-host slices, callers pass ``jax.devices()`` (all global devices)
and the mesh spans hosts; ICI carries the collectives within a slice and
DCN across slices — axis order puts ``"data"`` innermost so its
collectives ride ICI.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh


def make_mesh(n_data: Optional[int] = None, n_model: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a ``(model, data)`` mesh from the first ``n_model*n_data``
    devices (default: all).  ``data`` is the fastest-varying (innermost)
    axis so neighbouring mesh positions are ICI neighbours."""
    devices = list(devices if devices is not None else jax.devices())
    if n_data is None:
        n_data = len(devices) // n_model
    need = n_model * n_data
    if need > len(devices):
        raise ValueError(
            f"mesh wants {need} devices, only {len(devices)} available")
    grid = np.array(devices[:need]).reshape(n_model, n_data)
    return Mesh(grid, ("model", "data"))


def data_axis_size(mesh: Mesh) -> int:
    """GLOBAL size of the data axis (spans all hosts on a multi-host mesh)."""
    return mesh.shape["data"]
