"""Mesh + collective layer: the TPU replacement for the reference's entire
communication fabric (MongoDB job board + GridFS/NFS/scp file movement,
SURVEY.md §2.11).  Intermediate data never leaves HBM: hash-partitioned
records move between devices as one ``all_to_all`` inside the compiled
program, over ICI — the design inversion BASELINE.json calls the north
star ("replace polled shared state with compiled collectives").
"""

from .mesh import (  # noqa: F401
    LINK_CLASSES, data_axis_size, device_link_matrix, link_class,
    link_peaks, make_mesh)
from .shuffle import partition_exchange, Exchanged  # noqa: F401
from .partition import (  # noqa: F401
    UnmatchedLeafError, match_partition_rules, shard_tree)
