"""Ring attention: sequence/context parallelism over the mesh ring.

The reference has NO long-context machinery (SURVEY.md §5 "Long-context /
sequence parallelism: absent") — its only notion of length is streaming
file splits.  A TPU-native framework must scale sequence length across
devices (brief requirement), and the idiomatic construct is ring
attention: shard the sequence over the ``data`` axis, keep Q resident,
and rotate K/V blocks around the ICI ring with ``lax.ppermute`` while
accumulating attention in the numerically-stable online-softmax form
(flash-attention accumulation).  Peak memory per device is O(T_local²)
instead of O(T_global²), and the K/V transfer overlaps compute around the
ring.

Layout: inputs are the LOCAL sequence block ``[batch, t_local, heads,
head_dim]`` inside ``shard_map`` over *axis_name*; the global sequence is
the concatenation over mesh positions, in axis order.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _block_attn(q, k, v, mask, scale):
    """One (Q-block, KV-block) partial attention in online-softmax form.

    Returns ``(block_max [B,H,Tq], exp-weights sum [B,H,Tq],
    weighted V [B,Tq,H,D])`` — un-normalised pieces for the accumulator.

    Mixed precision: the two matmuls run in the INPUT dtype (bf16 keeps
    them on the MXU fast path) with f32 accumulation
    (preferred_element_type); softmax statistics are always f32.
    """
    # [B, H, Tq, Tk] — f32 accumulation regardless of operand dtype
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    scores = jnp.where(mask, scores, -jnp.inf)
    m = scores.max(axis=-1)  # [B, H, Tq]
    # guard fully-masked rows (all -inf): exp(-inf - -inf) would be NaN
    safe_m = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(scores - safe_m[..., None])
    p = jnp.where(mask, p, 0.0)
    den = p.sum(axis=-1)  # [B, H, Tq]
    num = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return safe_m, den, num


def _combine(m, den, num, bm, bden, bnum):
    """Fold one partial-attention block into the online-softmax
    accumulator (associative, so ring steps and local chunks share it)."""
    new_m = jnp.maximum(m, bm)
    corr_old = jnp.exp(m - new_m)
    corr_new = jnp.exp(bm - new_m)
    den = den * corr_old + bden * corr_new
    # broadcast the [B,H,T] corrections over the [B,T,H,D] accumulator
    num = (num * jnp.moveaxis(corr_old, 1, 2)[..., None]
           + bnum * jnp.moveaxis(corr_new, 1, 2)[..., None])
    return new_m, den, num


def _ring_attention_flash(q, k, v, axis_name, causal, scale,
                          interpret=None):
    """Ring attention with the Pallas kernel as each step's local
    compute (ops/flash_attention.py).  A ring step sees KV from rank
    ``src = rank - s``: blocks BEFORE mine are fully unmasked (plain
    attention), my own block is standard causal, blocks AFTER mine are
    fully masked — so the three cases dispatch to the existing kernel
    via ``lax.cond`` (causal=False / causal=True / skip) and no
    offset-masking kernel variant is needed.  Per-step partials combine
    in (out, lse) log-sum-exp form; the kernel's custom vjp carries the
    lse cotangent, so the whole ring differentiates.

    Layout: converts to the kernel's [B, H, T, D] at the boundary and
    rotates K/V in that layout (same bytes over ICI)."""
    from ..ops.flash_attention import NEG_INF, flash_attention_lse

    P = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    qk = jnp.swapaxes(q, 1, 2)  # [B, H, T, D]
    kk = jnp.swapaxes(k, 1, 2)
    vk = jnp.swapaxes(v, 1, 2)
    B, H, T, D = qk.shape
    scale = scale if scale is not None else 1.0 / (D ** 0.5)

    def attend(kv_causal, k_blk, v_blk):
        o, l = flash_attention_lse(qk, k_blk, v_blk, causal=kv_causal,
                                   scale=scale, interpret=interpret)
        return o.astype(jnp.float32), l

    def step(carry, s):
        k_blk, v_blk, out, lse = carry
        src = (rank - s) % P
        if causal:
            o_s, l_s = jax.lax.cond(
                src == rank,
                lambda: attend(True, k_blk, v_blk),
                lambda: jax.lax.cond(
                    src < rank,
                    lambda: attend(False, k_blk, v_blk),
                    # fully-masked step: contributes nothing
                    lambda: (jnp.zeros_like(out),
                             jnp.full_like(lse, NEG_INF))))
        else:
            o_s, l_s = attend(False, k_blk, v_blk)
        new_lse = jnp.logaddexp(lse, l_s)
        w_old = jnp.exp(lse - new_lse)
        w_new = jnp.exp(l_s - new_lse)
        out = out * w_old + o_s * w_new
        perm = [(i, (i + 1) % P) for i in range(P)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, out, new_lse), None

    # accumulators derived from q inherit its vma (same trick as the jnp
    # path); lse at NEG_INF with out zeros combines to zeros, no NaN
    out0 = qk.astype(jnp.float32) * 0.0
    lse0 = (qk[..., :1].astype(jnp.float32) * 0.0) + NEG_INF
    (k_f, v_f, out, lse), _ = jax.lax.scan(
        step, (kk, vk, out0, lse0), jnp.arange(P))
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str, causal: bool = True,
                   scale: Optional[float] = None,
                   block_size: Optional[int] = None,
                   use_flash: Optional[bool] = None) -> jax.Array:
    """Exact multi-head attention over a sequence sharded on *axis_name*.

    ``q/k/v``: [B, T_local, H, D] local blocks (must run inside
    ``shard_map``).  Returns [B, T_local, H, D].

    ``block_size`` additionally chunks each ring step's LOCAL attention
    (flash-attention style) over BOTH the query and key/value axes:
    scores materialise as [B, H, block, block] instead of
    [B, H, T_local, T_local], with each tile rematerialised in the
    backward pass — O(block²) attention memory regardless of T_local,
    the single-device half of the long-context story (the ring supplies
    the cross-device half).  Must divide T_local; None = one chunk
    (exact same math either way: the online-softmax combine is
    associative).

    ``use_flash`` (None = auto: on TPU) runs each ring step's local
    attention through the Pallas kernel instead of the jnp path — the
    kernel already tiles, so ``block_size`` is ignored there.
    """
    if use_flash is None:
        use_flash = jax.default_backend() == "tpu"
    if use_flash:
        return _ring_attention_flash(q, k, v, axis_name, causal, scale)
    P = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    B, T, H, D = q.shape
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    block = block_size or T
    if T % block != 0:
        raise ValueError(f"block_size {block} must divide T_local {T}")
    C = T // block

    q_pos = rank * T + jnp.arange(T)  # global positions of my queries

    def tile_step(carry, xs, q_c, qp_c):
        """Fold one KV tile into one Q chunk's accumulator."""
        m_c, den_c, num_c = carry
        kb, vb, kp = xs  # [B, block, H, D] x2, [block]
        if causal:
            mask = kp[None, :] <= qp_c[:, None]  # [Tq_c, Tk_c]
        else:
            mask = jnp.ones((qp_c.shape[0], kp.shape[0]), bool)
        bm, bden, bnum = _block_attn(q_c, kb, vb, mask[None, None], scale)
        return _combine(m_c, den_c, num_c, bm, bden, bnum), None

    def step(carry, s):
        k_blk, v_blk, m, den, num = carry
        # the block currently held arrived from rank - s (ring order)
        src = (rank - s) % P
        kv_pos = src * T + jnp.arange(T)
        if C == 1:
            (m, den, num), _ = tile_step((m, den, num),
                                         (k_blk, v_blk, kv_pos),
                                         q, q_pos)
        else:
            # flash tiling: outer scan over Q chunks (each with its own
            # accumulator slice), inner scan over KV tiles; each tile
            # recomputed in the backward pass (jax.checkpoint) so only
            # one [B, H, block, block] score tile ever exists
            kc = jnp.moveaxis(k_blk.reshape(B, C, block, H, D), 1, 0)
            vc = jnp.moveaxis(v_blk.reshape(B, C, block, H, D), 1, 0)
            kp_c = kv_pos.reshape(C, block)

            def q_step(_, xs):
                q_c, qp_c, m_c, den_c, num_c = xs
                inner = jax.checkpoint(
                    lambda cry, ys: tile_step(cry, ys, q_c, qp_c))
                (m_c, den_c, num_c), _ = jax.lax.scan(
                    inner, (m_c, den_c, num_c), (kc, vc, kp_c))
                return None, (m_c, den_c, num_c)

            qc = jnp.moveaxis(q.reshape(B, C, block, H, D), 1, 0)
            qp = q_pos.reshape(C, block)
            mc = jnp.moveaxis(m.reshape(B, H, C, block), 2, 0)
            denc = jnp.moveaxis(den.reshape(B, H, C, block), 2, 0)
            numc = jnp.moveaxis(num.reshape(B, C, block, H, D), 1, 0)
            _, (mc, denc, numc) = jax.lax.scan(
                q_step, None, (qc, qp, mc, denc, numc))
            m = jnp.moveaxis(mc, 0, 2).reshape(B, H, T)
            den = jnp.moveaxis(denc, 0, 2).reshape(B, H, T)
            num = jnp.moveaxis(numc, 0, 1).reshape(B, T, H, D)
        # rotate K/V to the next device; after P-1 rotations every device
        # has seen every block
        perm = [(i, (i + 1) % P) for i in range(P)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, m, den, num), None

    # the scan carry must enter with the same device-varying type the body
    # produces; deriving the zero accumulators from q inherits q's vma
    # regardless of how many mesh axes enclose us (sp alone, or sp x tp).
    # Accumulators are f32 even for bf16 inputs (online-softmax stats and
    # the weighted-V sum must not round per ring step).
    stat0 = jnp.moveaxis(q[..., 0].astype(jnp.float32) * 0.0, 1, 2)
    m0 = stat0 - jnp.inf      # [B, H, T]
    den0 = stat0
    num0 = q.astype(jnp.float32) * 0.0
    (k_f, v_f, m, den, num), _ = jax.lax.scan(
        step, (k, v, m0, den0, num0), jnp.arange(P))

    den = jnp.moveaxis(den, 1, 2)[..., None]  # [B, T, H, 1]
    return (num / jnp.maximum(den, 1e-20)).astype(q.dtype)


def full_attention_reference(q, k, v, causal: bool = True,
                             scale: Optional[float] = None) -> jax.Array:
    """Unsharded oracle: plain softmax attention over the GLOBAL sequence
    ([B, T, H, D]); tests diff ring_attention against this."""
    B, T, H, D = q.shape
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)
