"""Ring attention: sequence/context parallelism over the mesh ring.

The reference has NO long-context machinery (SURVEY.md §5 "Long-context /
sequence parallelism: absent") — its only notion of length is streaming
file splits.  A TPU-native framework must scale sequence length across
devices (brief requirement), and the idiomatic construct is ring
attention: shard the sequence over the ``data`` axis, keep Q resident,
and rotate K/V blocks around the ICI ring with ``lax.ppermute`` while
accumulating attention in the numerically-stable online-softmax form
(flash-attention accumulation).  Peak memory per device is O(T_local²)
instead of O(T_global²), and the K/V transfer overlaps compute around the
ring.

Layout: inputs are the LOCAL sequence block ``[batch, t_local, heads,
head_dim]`` inside ``shard_map`` over *axis_name*; the global sequence is
the concatenation over mesh positions, in axis order.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _block_attn(q, k, v, mask, scale):
    """One (Q-block, KV-block) partial attention in online-softmax form.

    Returns ``(block_max [B,H,Tq], exp-weights sum [B,H,Tq],
    weighted V [B,Tq,H,D])`` — un-normalised pieces for the accumulator.
    """
    # [B, H, Tq, Tk]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    scores = jnp.where(mask, scores, -jnp.inf)
    m = scores.max(axis=-1)  # [B, H, Tq]
    # guard fully-masked rows (all -inf): exp(-inf - -inf) would be NaN
    safe_m = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(scores - safe_m[..., None])
    p = jnp.where(mask, p, 0.0)
    den = p.sum(axis=-1)  # [B, H, Tq]
    num = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return safe_m, den, num


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str, causal: bool = True,
                   scale: Optional[float] = None) -> jax.Array:
    """Exact multi-head attention over a sequence sharded on *axis_name*.

    ``q/k/v``: [B, T_local, H, D] local blocks (must run inside
    ``shard_map``).  Returns [B, T_local, H, D].
    """
    P = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    B, T, H, D = q.shape
    scale = scale if scale is not None else 1.0 / (D ** 0.5)

    q_pos = rank * T + jnp.arange(T)  # global positions of my queries

    def step(carry, s):
        k_blk, v_blk, m, den, num = carry
        # the block currently held arrived from rank - s (ring order)
        src = (rank - s) % P
        kv_pos = src * T + jnp.arange(T)
        if causal:
            mask = kv_pos[None, :] <= q_pos[:, None]   # [Tq, Tk]
        else:
            mask = jnp.ones((T, T), bool)
        bm, bden, bnum = _block_attn(q, k_blk, v_blk,
                                     mask[None, None], scale)
        new_m = jnp.maximum(m, bm)
        corr_old = jnp.exp(m - new_m)
        corr_new = jnp.exp(bm - new_m)
        den = den * corr_old + bden * corr_new
        # broadcast the [B,H,T] corrections over the [B,T,H,D] accumulator
        num = (num * jnp.moveaxis(corr_old, 1, 2)[..., None]
               + bnum * jnp.moveaxis(corr_new, 1, 2)[..., None])
        # rotate K/V to the next device; after P-1 rotations every device
        # has seen every block
        perm = [(i, (i + 1) % P) for i in range(P)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, new_m, den, num), None

    # the scan carry must enter with the same device-varying type the body
    # produces; deriving the zero accumulators from q inherits q's vma
    # regardless of how many mesh axes enclose us (sp alone, or sp x tp)
    stat0 = jnp.moveaxis(q[..., 0] * 0.0, 1, 2)  # [B, H, T] zeros
    m0 = stat0 - jnp.inf
    den0 = stat0
    num0 = q * 0.0
    (k_f, v_f, m, den, num), _ = jax.lax.scan(
        step, (k, v, m0, den0, num0), jnp.arange(P))

    den = jnp.moveaxis(den, 1, 2)[..., None]  # [B, T, H, 1]
    return num / jnp.maximum(den, 1e-20)


def full_attention_reference(q, k, v, causal: bool = True,
                             scale: Optional[float] = None) -> jax.Array:
    """Unsharded oracle: plain softmax attention over the GLOBAL sequence
    ([B, T, H, D]); tests diff ring_attention against this."""
    B, T, H, D = q.shape
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)
