"""Regex partition rules: one declarative table maps leaf paths to
``PartitionSpec``s across params AND optimizer state.

The trainers used to hand-thread a per-model ``param_spec(name)``
function and rely on ``jit(opt.init)`` inheriting placements for the
optimizer moments — two different mechanisms for one layout decision,
and nothing that could name an optax leaf like ``1/0/trace/w0``.  This
module is the `match_partition_rules` pattern (SNIPPETS.md [3]) applied
uniformly to ANY pytree:

  * every leaf gets a ``/``-joined path name built from its pytree keys
    (dict keys, namedtuple fields, sequence indices), so a parameter and
    its momentum mirror (``w0`` and ``1/0/trace/w0``) match the SAME
    trailing-name rule;
  * scalar and single-element leaves pass through replicated (``P()``)
    without consulting the rules — optimizer step counters must never be
    sharded by an over-eager regex;
  * an unmatched non-scalar leaf is a LOUD :class:`UnmatchedLeafError`
    naming every offender — a silently replicated weight matrix is a
    memory-blowup-in-waiting on a real mesh, not a default.

These rule tables are also what the sharded checkpoint layer
(models/checkpoint.py) resolves restore placements from: the same regex
table lays state out on WHATEVER mesh the restoring process built,
which is what makes reshard-on-restore a non-event.
"""

from __future__ import annotations

import re
from typing import Any, List, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.tree_util import tree_flatten_with_path, tree_unflatten

#: a rule table: ``(regex, PartitionSpec)`` pairs, first match wins
#: (``re.search`` semantics — anchor with ``$`` to match trailing leaf
#: names so the table covers optimizer mirrors for free).
Rules = Sequence[Tuple[str, P]]


class UnmatchedLeafError(ValueError):
    """A non-scalar leaf matched no partition rule.  Loud by design:
    falling back to replicated would silently change the memory story
    of every mesh the state lands on."""


def _key_name(entry: Any) -> str:
    """One pytree path entry -> its string form (DictKey / GetAttrKey /
    SequenceKey / FlattenedIndexKey all carry exactly one of these)."""
    for attr in ("key", "name", "idx"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def leaf_path(path: Tuple[Any, ...]) -> str:
    """``/``-joined path name for a flattened pytree leaf."""
    return "/".join(_key_name(e) for e in path)


def flatten_with_names(tree: Any) -> Tuple[List[Tuple[str, Any]], Any]:
    """Flatten *tree* to ``([(path_name, leaf), ...], treedef)`` in
    canonical (tree_flatten) leaf order."""
    flat, treedef = tree_flatten_with_path(tree)
    return [(leaf_path(path), leaf) for path, leaf in flat], treedef


def resolve_spec(rules: Rules, name: str, leaf: Any) -> P:
    """The spec for ONE named leaf: scalar passthrough, then first
    matching rule, else :class:`UnmatchedLeafError`."""
    shape = tuple(getattr(leaf, "shape", np.shape(leaf)))
    if len(shape) == 0 or int(np.prod(shape)) == 1:
        return P()  # scalars/singletons (optax counts) always replicate
    for rx, ps in rules:
        if re.search(rx, name) is not None:
            return ps
    raise UnmatchedLeafError(
        f"no partition rule matches leaf {name!r} (shape {shape}); "
        "add a rule (or an explicit catch-all) — silent replication "
        "is not a default")


def match_partition_rules(rules: Rules, tree: Any) -> Any:
    """A *tree*-shaped pytree of ``PartitionSpec``s resolved from
    *rules* (the SNIPPETS.md [3] contract).  Raises
    :class:`UnmatchedLeafError` naming EVERY unmatched leaf at once."""
    named, treedef = flatten_with_names(tree)
    specs: List[P] = []
    unmatched: List[str] = []
    for name, leaf in named:
        try:
            specs.append(resolve_spec(rules, name, leaf))
        except UnmatchedLeafError:
            unmatched.append(name)
            specs.append(P())
    if unmatched:
        raise UnmatchedLeafError(
            "no partition rule matches: " + ", ".join(unmatched))
    return tree_unflatten(treedef, specs)


def shard_tree(tree: Any, rules: Rules, mesh: Mesh) -> Any:
    """``device_put`` every leaf of *tree* onto *mesh* with its
    rule-resolved ``NamedSharding`` — the one placement path for params
    and optimizer state alike (init AND reshard-on-restore)."""
    named, treedef = flatten_with_names(tree)
    placed = [
        jax.device_put(leaf,
                       NamedSharding(mesh, resolve_spec(rules, name, leaf)))
        for name, leaf in named]
    return tree_unflatten(treedef, placed)
