"""Worker: the polling executor loop (reference mapreduce/worker.lua).

Claims jobs from the task's job board, runs them under an exception shield
that marks the job BROKEN and reports to the errors channel, backs off
exponentially when idle, and self-terminates after too many distinct
failures (worker.lua:42-138, call stack SURVEY.md §3.2).  New vs the
reference: a heartbeat thread extends the RUNNING job's lease so the server
can distinguish slow workers from dead ones (SURVEY.md §5 gap).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional

from .coord.connection import Connection
from .coord.job import Job
from .coord.task import Task
from .utils.constants import (
    TASK_STATUS, DEFAULT_SLEEP, DEFAULT_MAX_SLEEP, DEFAULT_MAX_ITER,
    DEFAULT_MAX_TASKS, DEFAULT_HEARTBEAT, MAX_WORKER_RETRIES)

logger = logging.getLogger("mapreduce_tpu.worker")


class Worker:
    """Reference: ``worker.new(connstr, dbname, auth)`` (worker.lua:154-167)."""

    def __init__(self, connstr: str, dbname: str,
                 auth: Optional[Any] = None,
                 name: Optional[str] = None) -> None:
        self.cnn = Connection(connstr, dbname, auth)
        self.task = Task(self.cnn)
        self.name = name or f"{Connection.hostname()}-{id(self):x}"
        self.max_iter = DEFAULT_MAX_ITER
        self.max_sleep = DEFAULT_MAX_SLEEP
        self.max_tasks = DEFAULT_MAX_TASKS
        self.sleep = DEFAULT_SLEEP
        self.heartbeat_period = DEFAULT_HEARTBEAT
        self.jobs_done = 0

    def configure(self, conf: Dict[str, Any]) -> None:
        """worker.lua:142-148: max_iter / max_sleep / max_tasks knobs."""
        for k in ("max_iter", "max_sleep", "max_tasks"):
            if k in conf:
                setattr(self, k, conf[k])

    # -- one job under heartbeat ------------------------------------------

    def _run_job(self, job: Job) -> None:
        stop = threading.Event()

        def beat() -> None:
            while not stop.wait(self.heartbeat_period):
                try:
                    self.task.heartbeat(job.tbl)
                except Exception:  # heartbeat must never kill the job
                    logger.exception("heartbeat failed")

        t = threading.Thread(target=beat, daemon=True)
        t.start()
        try:
            job.execute()
        finally:
            stop.set()
            t.join()

    # -- the executor loop (worker.lua:42-105) ----------------------------

    def _execute_task(self) -> bool:
        """Work one task to completion; True if any job was executed."""
        iter_count = 0
        sleep = self.sleep
        worked = False
        failures = 0
        while iter_count < self.max_iter:
            job_tbl, status = self.task.take_next_job(
                self.name, Task.tmpname())
            if job_tbl is not None:
                job = Job(self.cnn, job_tbl, status, self.task.tbl,
                          self.task.jobs_ns())
                logger.info("%s: running %s job %s", self.name,
                            status.value, job.get_id())
                try:
                    self._run_job(job)
                    if status == TASK_STATUS.MAP:
                        self.task.note_written_map_job(job.get_id())
                    self.jobs_done += 1
                    worked = True
                except Exception as exc:
                    # xpcall shield: mark BROKEN, report, maybe give up
                    # (worker.lua:112-138)
                    logger.exception("%s: job %s failed", self.name,
                                     job.get_id())
                    job.mark_as_broken()
                    self.cnn.insert_exception(self.name, exc)
                    failures += 1
                    if failures >= MAX_WORKER_RETRIES:
                        logger.error(
                            "%s: %d failures, giving up on task "
                            "(worker.lua:133-137)", self.name, failures)
                        return worked
                iter_count = 0
                sleep = self.sleep
                continue
            if status == TASK_STATUS.FINISHED:
                return worked
            # idle: exponential backoff (worker.lua:97-103)
            iter_count += 1
            time.sleep(sleep)
            sleep = min(sleep * 1.5, self.max_sleep)
        return worked

    def execute(self) -> None:
        """Top-level entry (worker.lua:112-138): serve up to max_tasks
        tasks, waiting for each to appear."""
        logger.info("worker %s starting", self.name)
        for _ in range(self.max_tasks):
            # wait for a task document to exist and leave WAIT
            iter_count = 0
            sleep = self.sleep
            while iter_count < self.max_iter:
                if self.task.update() and not self.task.finished():
                    if self.task.status() != TASK_STATUS.WAIT:
                        break
                iter_count += 1
                time.sleep(sleep)
                sleep = min(sleep * 1.5, self.max_sleep)
            else:
                logger.info("worker %s: no task appeared, exiting", self.name)
                return
            self._execute_task()
        logger.info("worker %s done (%d jobs)", self.name, self.jobs_done)


def spawn_worker_threads(connstr: str, dbname: str, n: int,
                         conf: Optional[Dict[str, Any]] = None,
                         auth: Optional[Any] = None,
                         ) -> List[threading.Thread]:
    """Run *n* workers as daemon threads in this process — the rebuild's
    'fake cluster' for tests and the single-host deployment (the reference
    uses N OS processes under ``screen``, test.sh:10)."""
    threads = []
    for i in range(n):
        w = Worker(connstr, dbname, auth=auth, name=f"w{i}")
        if conf:
            w.configure(conf)
        t = threading.Thread(target=w.execute, daemon=True,
                             name=f"mr-worker-{i}")
        t.start()
        threads.append(t)
    return threads
