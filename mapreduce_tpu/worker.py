"""Worker: the polling executor loop (reference mapreduce/worker.lua).

Claims jobs from the task's job board, runs them under an exception shield
that marks the job BROKEN and reports to the errors channel, backs off
exponentially when idle, and self-terminates after too many CONSECUTIVE
failures (worker.lua:42-138, call stack SURVEY.md §3.2).  New vs the
reference: a heartbeat thread extends the RUNNING job's lease so the server
can distinguish slow workers from dead ones (SURVEY.md §5 gap) — and the
heartbeat doubles as the fencing probe: when it learns the lease is LOST
(reaped after a partition outlasted ``job_lease``, or re-issued to another
worker) it fences the running job, which aborts at its next emit/output
step instead of racing the re-issued copy (coord/task.LeaseLostError).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional

from .coord.connection import Connection
from .coord.job import Job
from .coord.task import LeaseLostError, Task
from .obs import metrics as _metrics
from .obs.trace import TRACER
from .utils.constants import (
    TASK_STATUS, DEFAULT_SLEEP, DEFAULT_MAX_SLEEP, DEFAULT_MAX_ITER,
    DEFAULT_MAX_TASKS, DEFAULT_HEARTBEAT, MAX_WORKER_RETRIES)

logger = logging.getLogger("mapreduce_tpu.worker")

_CLAIMS = _metrics.counter(
    "mrtpu_worker_claims_total",
    "claim-poll outcomes (labels: worker, outcome=claimed|idle|"
    "unreachable)")
_HEARTBEATS = _metrics.counter(
    "mrtpu_worker_heartbeats_total",
    "heartbeat outcomes (labels: worker, outcome=ok|error|lost)")
_LEASE_LOST = _metrics.counter(
    "mrtpu_worker_lease_lost_total",
    "jobs fenced after a confirmed lease loss (labels: worker)")
_JOBS = _metrics.counter(
    "mrtpu_worker_jobs_total",
    "jobs this worker finished, by outcome (labels: worker, phase, "
    "outcome=written|broken|fenced)")
_JOB_SECONDS = _metrics.histogram(
    "mrtpu_worker_job_seconds",
    "wall seconds from claim to job outcome (labels: worker, phase)")
_CONSEC_FAILURES = _metrics.gauge(
    "mrtpu_worker_consecutive_failures",
    "current unbroken run of job failures (labels: worker); "
    "MAX_WORKER_RETRIES ends the worker")


class Worker:
    """Reference: ``worker.new(connstr, dbname, auth)`` (worker.lua:154-167)."""

    def __init__(self, connstr: str, dbname: str,
                 auth: Optional[Any] = None,
                 name: Optional[str] = None,
                 retry: Optional[Any] = None) -> None:
        self.cnn = Connection(connstr, dbname, auth, retry=retry)
        self.task = Task(self.cnn)
        self.name = name or f"{Connection.hostname()}-{id(self):x}"
        self.max_iter = DEFAULT_MAX_ITER
        self.max_sleep = DEFAULT_MAX_SLEEP
        self.max_tasks = DEFAULT_MAX_TASKS
        self.sleep = DEFAULT_SLEEP
        self.heartbeat_period = DEFAULT_HEARTBEAT
        self.jobs_done = 0
        #: fence of the most recently started job — observable so
        #: tests/operators can see a fencing in flight
        self.current_fence: Optional[threading.Event] = None

    def configure(self, conf: Dict[str, Any]) -> None:
        """worker.lua:142-148: max_iter / max_sleep / max_tasks knobs."""
        for k in ("max_iter", "max_sleep", "max_tasks"):
            if k in conf:
                setattr(self, k, conf[k])

    # -- one job under heartbeat ------------------------------------------

    def _run_job(self, job: Job, fence: threading.Event) -> None:
        stop = threading.Event()

        def beat() -> None:
            while not stop.wait(self.heartbeat_period):
                try:
                    owned = self.task.heartbeat(job.tbl)
                except Exception:
                    # network failure: ownership is UNKNOWN (the lease may
                    # still be live server-side), so keep beating — fencing
                    # on a guess would abort healthy jobs during a blip
                    _HEARTBEATS.inc(worker=self.name, outcome="error")
                    logger.exception("heartbeat failed")
                    continue
                _HEARTBEATS.inc(worker=self.name,
                                outcome="ok" if owned else "lost")
                if not owned and not stop.is_set():
                    # (the heartbeat query matches this claim's WRITTEN
                    # too, so completion races report ownership; the stop
                    # check is a second belt for shutdown edges)
                    # the server answered and the claim no longer matches:
                    # lease reaped (partition outlasted job_lease,
                    # task.reap_expired) or the job was re-issued.  Fence:
                    # the running job aborts at its next emit/output step
                    # instead of racing the new owner.
                    logger.warning(
                        "%s: lease lost on job %s — fencing this run",
                        self.name, job.get_id())
                    _LEASE_LOST.inc(worker=self.name)
                    fence.set()
                    return

        t = threading.Thread(target=beat, daemon=True)
        t.start()
        try:
            job.execute()
        finally:
            stop.set()
            t.join()

    # -- the executor loop (worker.lua:42-105) ----------------------------

    def _execute_task(self) -> bool:
        """Work one task to completion; True if any job was executed."""
        iter_count = 0
        sleep = self.sleep
        worked = False
        failures = 0  # CONSECUTIVE failures; reset by every success
        while iter_count < self.max_iter:
            t_claim0 = time.monotonic()
            try:
                job_tbl, status = self.task.take_next_job(
                    self.name, Task.tmpname())
            except PermissionError:
                raise  # auth misconfig: no amount of retrying fixes it
            except OSError as exc:
                # board unreachable (RetryError / CircuitOpenError /
                # reset): an idle poll, not a death sentence — back off
                # like any idle iteration; a board that never comes back
                # exhausts max_iter and the worker exits normally
                _CLAIMS.inc(worker=self.name, outcome="unreachable")
                logger.warning("%s: job board unreachable (%s); "
                               "backing off", self.name, exc)
                iter_count += 1
                time.sleep(sleep)
                sleep = min(sleep * 1.5, self.max_sleep)
                continue
            t_claim1 = time.monotonic()
            if job_tbl is not None:
                _CLAIMS.inc(worker=self.name, outcome="claimed")
                fence = threading.Event()
                self.current_fence = fence
                job = Job(self.cnn, job_tbl, status, self.task.tbl,
                          self.task.jobs_ns(), fence=fence)
                logger.info("%s: running %s job %s", self.name,
                            status.value, job.get_id())
                outcome = "written"
                # the root span is backdated to the claim RPC so the
                # trace shows claim -> run -> write nested under one
                # per-job trace id (the acceptance-criterion shape)
                with TRACER.span("job", start=t_claim0,
                                 job=job.get_id(), phase=status.value,
                                 worker=self.name) as root:
                    TRACER.record("claim", t_claim0, t_claim1,
                                  worker=self.name, job=job.get_id())
                    try:
                        self._run_job(job, fence)
                        if status == TASK_STATUS.MAP:
                            self.task.note_written_map_job(job.get_id())
                        self.jobs_done += 1
                        worked = True
                        # a success proves this worker is healthy: only an
                        # unbroken run of failures should end it, or a
                        # long-lived worker's occasional transient faults
                        # accumulate into a lifetime death sentence
                        failures = 0
                    except LeaseLostError:
                        # fenced, not failed: the job was reaped/re-issued
                        # (e.g. a partition outlasted job_lease) and its
                        # new owner runs it now.  This worker is healthy —
                        # don't mark BROKEN (the claim guard wouldn't
                        # match anyway), don't count it toward giving up.
                        outcome = "fenced"
                        logger.warning(
                            "%s: job %s fenced after lease loss",
                            self.name, job.get_id())
                    except Exception as exc:
                        # xpcall shield: mark BROKEN, report, maybe give up
                        # (worker.lua:112-138)
                        outcome = "broken"
                        logger.exception("%s: job %s failed", self.name,
                                         job.get_id())
                        try:
                            job.mark_as_broken()
                            self.cnn.insert_exception(self.name, exc)
                        except Exception:
                            # the BROKEN mark and the errors channel ride
                            # the same network as the board; when the job
                            # failed BECAUSE of a partition these fail
                            # too.  Keep the shield: the lease reaper
                            # re-issues the job either way, a dead worker
                            # thread helps nobody.
                            logger.exception(
                                "%s: could not report job failure",
                                self.name)
                        failures += 1
                    finally:
                        root.args["outcome"] = outcome
                        _JOBS.inc(worker=self.name, phase=status.value,
                                  outcome=outcome)
                        _JOB_SECONDS.observe(
                            time.monotonic() - t_claim0,
                            worker=self.name, phase=status.value)
                        _CONSEC_FAILURES.set(failures, worker=self.name)
                if failures >= MAX_WORKER_RETRIES:
                    logger.error(
                        "%s: %d consecutive failures, giving up on "
                        "task (worker.lua:133-137)", self.name,
                        failures)
                    return worked
                iter_count = 0
                sleep = self.sleep
                continue
            _CLAIMS.inc(worker=self.name, outcome="idle")
            if status == TASK_STATUS.FINISHED:
                return worked
            # idle: exponential backoff (worker.lua:97-103)
            iter_count += 1
            time.sleep(sleep)
            sleep = min(sleep * 1.5, self.max_sleep)
        return worked

    def execute(self) -> None:
        """Top-level entry (worker.lua:112-138): serve up to max_tasks
        tasks, waiting for each to appear."""
        logger.info("worker %s starting", self.name)
        for _ in range(self.max_tasks):
            # wait for a task document to exist and leave WAIT
            iter_count = 0
            sleep = self.sleep
            while iter_count < self.max_iter:
                try:
                    has_task = self.task.update()
                except PermissionError:
                    raise
                except OSError as exc:  # same shield as the claim loop
                    logger.warning("%s: job board unreachable (%s); "
                                   "backing off", self.name, exc)
                    has_task = False
                if has_task and not self.task.finished():
                    if self.task.status() != TASK_STATUS.WAIT:
                        break
                iter_count += 1
                time.sleep(sleep)
                sleep = min(sleep * 1.5, self.max_sleep)
            else:
                logger.info("worker %s: no task appeared, exiting", self.name)
                return
            self._execute_task()
        logger.info("worker %s done (%d jobs)", self.name, self.jobs_done)


def spawn_worker_threads(connstr: str, dbname: str, n: int,
                         conf: Optional[Dict[str, Any]] = None,
                         auth: Optional[Any] = None,
                         retry: Optional[Any] = None,
                         ) -> List[threading.Thread]:
    """Run *n* workers as daemon threads in this process — the rebuild's
    'fake cluster' for tests and the single-host deployment (the reference
    uses N OS processes under ``screen``, test.sh:10)."""
    threads = []
    for i in range(n):
        w = Worker(connstr, dbname, auth=auth, name=f"w{i}", retry=retry)
        if conf:
            w.configure(conf)
        t = threading.Thread(target=w.execute, daemon=True,
                             name=f"mr-worker-{i}")
        t.start()
        threads.append(t)
    return threads
