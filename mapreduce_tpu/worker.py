"""Worker: the polling executor loop (reference mapreduce/worker.lua).

Claims jobs from the task's job board, runs them under an exception shield
that marks the job BROKEN and reports to the errors channel, backs off
exponentially when idle, and self-terminates after too many CONSECUTIVE
failures (worker.lua:42-138, call stack SURVEY.md §3.2).  New vs the
reference:

* a heartbeat thread extends held-job leases so the server can tell slow
  workers from dead ones (SURVEY.md §5 gap) — and doubles as the fencing
  probe: when it learns a lease is LOST (reaped after a partition
  outlasted ``job_lease``, or re-issued to another worker) it fences
  that job, which aborts at its next emit/output step instead of racing
  the re-issued copy (coord/task.LeaseLostError);
* the claim path is PIPELINED: one batched claim RPC takes up to
  ``claim_batch`` jobs (Task.take_next_jobs — one board round trip
  instead of one per job), and when the worker starts its last queued
  job it claims the next batch in the background, so the claim's
  network latency overlaps the current job's execution instead of
  serializing with it.  Every held claim is leased and fenced
  INDIVIDUALLY — one heartbeat RPC covers them all (heartbeat_many),
  but a lost lease fences exactly the job that lost it.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .coord.connection import Connection
from .coord.job import Job
from .coord.task import LeaseLostError, Task
from .obs import metrics as _metrics
from .obs.trace import TRACER
from .utils.constants import (
    TASK_STATUS, DEFAULT_SLEEP, DEFAULT_MAX_SLEEP, DEFAULT_MAX_ITER,
    DEFAULT_MAX_TASKS, DEFAULT_HEARTBEAT, DEFAULT_CLAIM_BATCH,
    MAX_WORKER_RETRIES)

logger = logging.getLogger("mapreduce_tpu.worker")

_CLAIMS = _metrics.counter(
    "mrtpu_worker_claims_total",
    "claim-poll outcomes (labels: worker, task, outcome=claimed|idle|"
    "unreachable)")
_CLAIM_BATCH = _metrics.histogram(
    "mrtpu_worker_claim_batch_jobs",
    "jobs claimed per successful claim RPC (labels: worker, task) — the "
    "claim pipelining win is this histogram's mean being > 1",
    buckets=(1, 2, 4, 8, 16, 32))
_CLAIMED_JOBS = _metrics.counter(
    "mrtpu_worker_claimed_jobs_total",
    "jobs claimed, summed over batches (labels: worker, task)")
_RELEASED_JOBS = _metrics.counter(
    "mrtpu_worker_released_jobs_total",
    "claim-ahead jobs handed back to WAITING unrun at worker exit "
    "(labels: worker, task)")
_HEARTBEATS = _metrics.counter(
    "mrtpu_worker_heartbeats_total",
    "per-claim heartbeat outcomes (labels: worker, task, outcome=ok|"
    "error|lost); one batched RPC may account several claims")
_LEASE_LOST = _metrics.counter(
    "mrtpu_worker_lease_lost_total",
    "jobs fenced after a confirmed lease loss (labels: worker, task)")
_JOBS = _metrics.counter(
    "mrtpu_worker_jobs_total",
    "jobs this worker finished, by outcome (labels: worker, task, "
    "phase, outcome=written|broken|fenced)")
_JOB_SECONDS = _metrics.histogram(
    "mrtpu_worker_job_seconds",
    "wall seconds from claim to job outcome (labels: worker, task, "
    "phase)")
_CONSEC_FAILURES = _metrics.gauge(
    "mrtpu_worker_consecutive_failures",
    "current unbroken run of job failures (labels: worker, task); "
    "MAX_WORKER_RETRIES ends the worker")


class _AsyncClaim:
    """One batched claim RPC in flight on its own thread — the worker's
    claim-ahead slot.  Started when the worker begins its last queued
    job; joined when that job finishes, by which time the next batch is
    (usually) already claimed.  The claims are registered into the
    worker's held-lease set FROM THIS THREAD, the moment the RPC
    answers — a prefetched claim's lease starts ticking at the claim,
    so its heartbeats must too, not only once the current job finishes
    and the batch is dequeued (a long job would otherwise let every
    prefetched lease expire and be reaped, charging spurious
    repetitions)."""

    def __init__(self, worker: "Worker", sync: bool = False) -> None:
        self.t0 = time.monotonic()
        self.t1 = self.t0
        self.jobs: List[Dict[str, Any]] = []
        self.fences: Dict[str, threading.Event] = {}
        self.status: Optional[TASK_STATUS] = None
        self.task_tbl: Dict[str, Any] = {}
        self.error: Optional[BaseException] = None
        self._worker = worker
        if sync:
            # the blocking-claim path: same result shape, no thread
            # churn (an idle worker polls this many times per second)
            self._t = None
            self._run()
        else:
            self._t = threading.Thread(target=self._run, daemon=True)
            self._t.start()

    def _run(self) -> None:
        w = self._worker
        try:
            self.jobs, self.status = w.task.take_next_jobs(
                w.name, Task.tmpname(), w.claim_batch)
            self.task_tbl = dict(w.task.tbl)
        except BaseException as exc:
            self.error = exc
        else:
            if self.jobs:  # under heartbeat from this instant
                self.fences = w._register_claims(self.status, self.jobs)
        self.t1 = time.monotonic()

    def join(self) -> "_AsyncClaim":
        if self._t is not None:
            self._t.join()
        return self


class Worker:
    """Reference: ``worker.new(connstr, dbname, auth)`` (worker.lua:154-167)."""

    def __init__(self, connstr: str, dbname: str,
                 auth: Optional[Any] = None,
                 name: Optional[str] = None,
                 retry: Optional[Any] = None) -> None:
        self.cnn = Connection(connstr, dbname, auth, retry=retry)
        self.task = Task(self.cnn)
        self.name = name or f"{Connection.hostname()}-{id(self):x}"
        #: the per-task accounting label on every metric this worker
        #: emits (the task database name — low cardinality)
        self._task = dbname
        self.max_iter = DEFAULT_MAX_ITER
        self.max_sleep = DEFAULT_MAX_SLEEP
        self.max_tasks = DEFAULT_MAX_TASKS
        self.sleep = DEFAULT_SLEEP
        self.heartbeat_period = DEFAULT_HEARTBEAT
        #: claim pipelining knobs: jobs per claim RPC, and whether the
        #: next batch's claim overlaps the current job's execution
        self.claim_batch = DEFAULT_CLAIM_BATCH
        self.claim_ahead = True
        #: telemetry push knobs: spans + metric snapshots go to the
        #: docserver's collector every ``telemetry_interval`` seconds
        #: over a DEDICATED socket (obs/collector.TelemetryPusher —
        #: lossy-but-counted, can never block a heartbeat or job).
        #: ``telemetry_address`` defaults to the board itself for
        #: http:// connstrs.  The LIBRARY default is off (embedders —
        #: and tests that put a fault proxy in front of the board —
        #: must not grow surprise background traffic); the worker CLI
        #: turns it on at 1.0s.
        self.telemetry_interval = 0.0
        self.telemetry_address: Optional[str] = None
        self.telemetry_backlog = 20_000
        self.jobs_done = 0
        #: fence of the most recently started job — observable so
        #: tests/operators can see a fencing in flight
        self.current_fence: Optional[threading.Event] = None
        # claims this worker currently holds: _id -> (coll, job_tbl,
        # fence); shared between the executor loop and the heartbeat
        # thread under _held_lock
        self._held: Dict[str, Tuple[str, Dict[str, Any],
                                    threading.Event]] = {}
        self._held_lock = threading.Lock()

    def configure(self, conf: Dict[str, Any]) -> None:
        """worker.lua:142-148: max_iter / max_sleep / max_tasks knobs,
        plus the claim-pipelining pair and the telemetry-push knobs."""
        for k in ("max_iter", "max_sleep", "max_tasks", "claim_batch",
                  "claim_ahead", "telemetry_interval",
                  "telemetry_address", "telemetry_backlog"):
            if k in conf:
                setattr(self, k, conf[k])
        # claim_batch=0 would make every poll an idle poll forever — a
        # silent no-op worker; 1 is the meaningful minimum (serial path)
        self.claim_batch = max(int(self.claim_batch), 1)

    def _register_claims(self, status: TASK_STATUS,
                         jobs: List[Dict[str, Any]],
                         ) -> Dict[str, threading.Event]:
        """Put freshly claimed jobs under heartbeat coverage (called by
        whichever thread completed the claim RPC); returns each job's
        fence."""
        coll = self._jobs_coll(status)
        fences: Dict[str, threading.Event] = {}
        with self._held_lock:
            for j in jobs:
                fence = threading.Event()
                self._held[j["_id"]] = (coll, j, fence)
                fences[j["_id"]] = fence
        return fences

    # -- heartbeat: one thread, one RPC, every held lease -----------------

    def _beat_all(self, stop: threading.Event) -> None:
        while not stop.wait(self.heartbeat_period):
            with self._held_lock:
                groups: Dict[str, List[Tuple[Dict[str, Any],
                                             threading.Event]]] = {}
                for coll, job_tbl, fence in self._held.values():
                    groups.setdefault(coll, []).append((job_tbl, fence))
            for coll, pairs in groups.items():
                try:
                    owned = self.task.heartbeat_many(
                        coll, [j for j, _ in pairs])
                except Exception:
                    # network failure: ownership is UNKNOWN (the lease may
                    # still be live server-side), so keep beating — fencing
                    # on a guess would abort healthy jobs during a blip
                    _HEARTBEATS.inc(worker=self.name, task=self._task,
                                    outcome="error")
                    logger.exception("heartbeat failed")
                    continue
                for (job_tbl, fence), ok in zip(pairs, owned):
                    _HEARTBEATS.inc(worker=self.name, task=self._task,
                                    outcome="ok" if ok else "lost")
                    if not ok and not stop.is_set():
                        # the server answered and this claim no longer
                        # matches: lease reaped (partition outlasted
                        # job_lease, task.reap_expired) or the job was
                        # re-issued.  Fence THIS job only — its batch-
                        # mates' claims answered for themselves.
                        logger.warning(
                            "%s: lease lost on job %s — fencing",
                            self.name, job_tbl["_id"])
                        _LEASE_LOST.inc(worker=self.name, task=self._task)
                        fence.set()
                        with self._held_lock:
                            self._held.pop(job_tbl["_id"], None)

    # -- one job under the shield (worker.lua:112-138) --------------------

    def _run_one(self, job_tbl: Dict[str, Any], status: TASK_STATUS,
                 task_tbl: Dict[str, Any], coll: str,
                 fence: threading.Event,
                 t_claim0: float, t_claim1: float) -> str:
        """Execute one claimed job; returns its outcome
        (written|broken|fenced)."""
        self.current_fence = fence
        job = Job(self.cnn, job_tbl, status, task_tbl, coll, fence=fence)
        logger.info("%s: running %s job %s", self.name, status.value,
                    job.get_id())
        outcome = "written"
        # the root span is backdated to the claim RPC so the trace shows
        # claim -> run -> write nested under one per-job trace id (the
        # batch's claim interval is recorded under EACH of its jobs)
        with TRACER.span("job", start=t_claim0, job=job.get_id(),
                         phase=status.value, worker=self.name) as root:
            TRACER.record("claim", t_claim0, t_claim1,
                          worker=self.name, job=job.get_id())
            try:
                job.execute()
                if status == TASK_STATUS.MAP:
                    self.task.note_written_map_job(job.get_id())
                self.jobs_done += 1
            except LeaseLostError:
                # fenced, not failed: the job was reaped/re-issued (e.g. a
                # partition outlasted job_lease) and its new owner runs it
                # now.  This worker is healthy — don't mark BROKEN (the
                # claim guard wouldn't match anyway), don't count it
                # toward giving up.
                outcome = "fenced"
                logger.warning("%s: job %s fenced after lease loss",
                               self.name, job.get_id())
            except Exception as exc:
                # xpcall shield: mark BROKEN, report, maybe give up
                # (worker.lua:112-138)
                outcome = "broken"
                logger.exception("%s: job %s failed", self.name,
                                 job.get_id())
                try:
                    job.mark_as_broken()
                    self.cnn.insert_exception(self.name, exc)
                except Exception:
                    # the BROKEN mark and the errors channel ride the same
                    # network as the board; when the job failed BECAUSE of
                    # a partition these fail too.  Keep the shield: the
                    # lease reaper re-issues the job either way, a dead
                    # worker thread helps nobody.
                    logger.exception("%s: could not report job failure",
                                     self.name)
            finally:
                root.args["outcome"] = outcome
                _JOBS.inc(worker=self.name, task=self._task,
                          phase=status.value, outcome=outcome)
                _JOB_SECONDS.observe(time.monotonic() - t_claim0,
                                     worker=self.name, task=self._task,
                                     phase=status.value)
        return outcome

    def _release(self, coll: str,
                 leftovers: List[Dict[str, Any]]) -> None:
        """Hand claimed-but-unrun jobs back to WAITING on exit paths so
        another worker picks them up now, not after a lease reap."""
        if not leftovers:
            return
        with self._held_lock:
            for j in leftovers:
                self._held.pop(j["_id"], None)
        try:
            n = self.task.release_jobs(coll, leftovers)
        except Exception:
            logger.warning("%s: could not release %d unrun claims; the "
                           "lease reaper will reclaim them", self.name,
                           len(leftovers), exc_info=True)
            return
        if n:
            _RELEASED_JOBS.inc(n, worker=self.name, task=self._task)

    def _jobs_coll(self, status: TASK_STATUS) -> str:
        return (self.task.map_jobs_ns() if status == TASK_STATUS.MAP
                else self.task.red_jobs_ns())

    # -- the executor loop (worker.lua:42-105) ----------------------------

    def _execute_task(self) -> bool:
        """Work one task to completion; True if any job was executed."""
        iter_count = 0
        sleep = self.sleep
        worked = False
        failures = 0  # CONSECUTIVE failures; reset by every success
        prefetch: Optional[_AsyncClaim] = None
        with self._held_lock:
            self._held.clear()
        stop_beat = threading.Event()
        beat_t = threading.Thread(target=self._beat_all,
                                  args=(stop_beat,), daemon=True)
        beat_t.start()
        try:
            while iter_count < self.max_iter:
                # -- obtain a batch: the claim-ahead slot if one is in
                #    flight, else a fresh (blocking) claim RPC
                if prefetch is not None:
                    claim, prefetch = prefetch.join(), None
                else:
                    claim = _AsyncClaim(self, sync=True)
                if claim.error is not None:
                    if isinstance(claim.error, PermissionError):
                        raise claim.error  # auth misconfig: retrying is no fix
                    if not isinstance(claim.error, OSError):
                        raise claim.error
                    # board unreachable (RetryError / CircuitOpenError /
                    # reset): an idle poll, not a death sentence — back off
                    # like any idle iteration; a board that never comes
                    # back exhausts max_iter and the worker exits normally
                    _CLAIMS.inc(worker=self.name, task=self._task,
                                outcome="unreachable")
                    logger.warning("%s: job board unreachable (%s); "
                                   "backing off", self.name, claim.error)
                    iter_count += 1
                    time.sleep(sleep)
                    sleep = min(sleep * 1.5, self.max_sleep)
                    continue
                if not claim.jobs:
                    _CLAIMS.inc(worker=self.name, task=self._task,
                                outcome="idle")
                    if claim.status == TASK_STATUS.FINISHED:
                        return worked
                    # idle: exponential backoff (worker.lua:97-103)
                    iter_count += 1
                    time.sleep(sleep)
                    sleep = min(sleep * 1.5, self.max_sleep)
                    continue

                status, task_tbl = claim.status, claim.task_tbl
                coll = self._jobs_coll(status)
                _CLAIMS.inc(worker=self.name, task=self._task,
                            outcome="claimed")
                _CLAIM_BATCH.observe(len(claim.jobs), worker=self.name,
                                     task=self._task)
                _CLAIMED_JOBS.inc(len(claim.jobs), worker=self.name,
                                  task=self._task)
                # fences were minted at registration time (inside the
                # claim RPC's thread) — the batch has been heartbeated
                # since the moment it was claimed
                pending = collections.deque(
                    (j, claim.fences[j["_id"]]) for j in claim.jobs)

                try:
                    while pending:
                        job_tbl, fence = pending.popleft()
                        if fence.is_set():
                            # lease lost while queued (already out of
                            # _held): the re-issued copy owns it — never
                            # start the stale run
                            logger.warning(
                                "%s: skipping job %s — lease lost before "
                                "it started", self.name, job_tbl["_id"])
                            continue
                        if not pending and self.claim_ahead:
                            # claim-ahead: the next batch's round trip
                            # overlaps this (last queued) job's execution
                            prefetch = _AsyncClaim(self)
                        outcome = self._run_one(
                            job_tbl, status, task_tbl, coll, fence,
                            claim.t0, claim.t1)
                        with self._held_lock:
                            self._held.pop(job_tbl["_id"], None)
                        if outcome == "written":
                            worked = True
                            # a success proves this worker is healthy:
                            # only an unbroken run of failures should end
                            # it, or a long-lived worker's occasional
                            # transient faults accumulate into a lifetime
                            # death sentence
                            failures = 0
                        elif outcome == "broken":
                            failures += 1
                        _CONSEC_FAILURES.set(failures, worker=self.name,
                                             task=self._task)
                        if failures >= MAX_WORKER_RETRIES:
                            logger.error(
                                "%s: %d consecutive failures, giving up "
                                "on task (worker.lua:133-137)", self.name,
                                failures)
                            return worked
                        if outcome == "broken":
                            # shed the rest of the batch (finally below
                            # releases it) and re-claim fresh: the serial
                            # path interleaves a failed job's RETRY with
                            # the next claims, so N distinct first-attempt
                            # failures never read as N consecutive ones —
                            # ploughing on through a claimed batch would.
                            # A failing worker also shouldn't sit on
                            # queued work another worker could run.
                            break
                finally:
                    # leftover claims on ANY exit (give-up, exception):
                    # back to WAITING for the next worker
                    self._release(coll, [j for j, f in pending
                                         if not f.is_set()])
                iter_count = 0
                sleep = self.sleep
            return worked
        finally:
            if prefetch is not None:
                c = prefetch.join()
                if c.error is None and c.jobs:
                    self._release(self._jobs_coll(c.status), c.jobs)
            stop_beat.set()
            beat_t.join()

    def _start_telemetry(self):
        """Lease the process-shared telemetry pusher when the board is a
        networked docserver (the collector lives there) — shared, not
        per-worker: N workers in one process drain ONE span ring, so one
        pusher delivers it once.  Any failure means 'no telemetry',
        never 'no worker'."""
        from .obs.collector import acquire_pusher

        address = self.telemetry_address
        if not address:
            try:
                address = self.cnn.board_hostport()
            except Exception:
                address = None
        return acquire_pusher(address, self.cnn.auth_token(),
                              role=f"worker:{self.name}",
                              interval=self.telemetry_interval,
                              max_backlog=self.telemetry_backlog)

    def execute(self) -> None:
        """Top-level entry (worker.lua:112-138): serve up to max_tasks
        tasks, waiting for each to appear."""
        from .obs.collector import release_pusher

        logger.info("worker %s starting", self.name)
        lease = self._start_telemetry()
        try:
            self._execute_tasks()
        finally:
            # the LAST worker out stops the shared pusher with a final
            # flush, so the process's closing spans reach the merged
            # timeline; anything undeliverable is counted dropped
            release_pusher(lease)

    def _execute_tasks(self) -> None:
        for _ in range(self.max_tasks):
            # wait for a task document to exist and leave WAIT
            iter_count = 0
            sleep = self.sleep
            while iter_count < self.max_iter:
                try:
                    has_task = self.task.update()
                except PermissionError:
                    raise
                except OSError as exc:  # same shield as the claim loop
                    logger.warning("%s: job board unreachable (%s); "
                                   "backing off", self.name, exc)
                    has_task = False
                if has_task and not self.task.finished():
                    if self.task.status() != TASK_STATUS.WAIT:
                        break
                iter_count += 1
                time.sleep(sleep)
                sleep = min(sleep * 1.5, self.max_sleep)
            else:
                logger.info("worker %s: no task appeared, exiting", self.name)
                return
            self._execute_task()
        logger.info("worker %s done (%d jobs)", self.name, self.jobs_done)


def spawn_worker_threads(connstr: str, dbname: str, n: int,
                         conf: Optional[Dict[str, Any]] = None,
                         auth: Optional[Any] = None,
                         retry: Optional[Any] = None,
                         name_prefix: Optional[str] = None,
                         ) -> List[threading.Thread]:
    """Run *n* workers as daemon threads in this process — the rebuild's
    'fake cluster' for tests and the single-host deployment (the reference
    uses N OS processes under ``screen``, test.sh:10).  *name_prefix*
    overrides the default ``w<i>`` naming (``<prefix>-<i>``) so
    multi-process deployments keep worker metric/trace labels distinct."""
    threads = []
    for i in range(n):
        name = f"{name_prefix}-{i}" if name_prefix else f"w{i}"
        w = Worker(connstr, dbname, auth=auth, name=name, retry=retry)
        if conf:
            w.configure(conf)
        t = threading.Thread(target=w.execute, daemon=True,
                             name=f"mr-worker-{i}")
        t.start()
        threads.append(t)
    return threads
