"""Timestamp every event in the wave pipeline to find the 20s."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
import jax.numpy as jnp
import bench
from mapreduce_tpu.engine import DeviceWordCount, EngineConfig
from mapreduce_tpu.ops.tokenize import shard_text
from mapreduce_tpu.parallel import make_mesh

corpus = bench.make_corpus()
mesh = make_mesh()
wc = DeviceWordCount(mesh, chunk_len=1 << 22,
                     config=EngineConfig(local_capacity=1 << 18,
                                         exchange_capacity=1 << 17,
                                         out_capacity=1 << 18))
n_chunks = -(-len(corpus) // wc.chunk_len)
chunks, L = shard_text(corpus, n_chunks, pad_multiple=wc.config.tile)
eng = wc._engine_for(L)
cfg = eng.config
fn = eng._get_compiled(cfg)
merge = eng._get_merge(cfg)

# warm everything
wi, n_real = eng._shard_inputs(chunks, 8)
outs = [fn(*(w if isinstance(w, tuple) else w.result()), n_real) for w in wi]
cat = lambda i: jnp.concatenate([o[i] for o in outs], axis=1)
m = merge(cat(0), cat(1), cat(2), cat(3))
jax.block_until_ready(m[0])
del wi, outs, m
print("warm", flush=True)

for trial in range(2):
    T0 = time.time()
    ts = lambda: f"{time.time()-T0:6.2f}"
    wave_inputs, n_real = eng._shard_inputs(chunks, 8)
    print(f"[{ts()}] puts submitted", flush=True)
    outs = []
    resolved = []
    for w in range(8):
        wi_ = wave_inputs[w]
        ci, ii = wi_ if isinstance(wi_, tuple) else wi_.result()
        print(f"[{ts()}] wave{w} put returned", flush=True)
        o = fn(ci, ii, n_real)
        print(f"[{ts()}] wave{w} dispatched", flush=True)
        outs.append(o); resolved.append(ci)
    m = merge(*[jnp.concatenate([o[i] for o in outs], axis=1)
                for i in range(4)])
    print(f"[{ts()}] merge dispatched", flush=True)
    jax.block_until_ready(resolved)
    print(f"[{ts()}] inputs ready", flush=True)
    for w, o in enumerate(outs):
        jax.block_until_ready(o[4])
        print(f"[{ts()}] wave{w} compute done", flush=True)
    jax.block_until_ready(m[0])
    print(f"[{ts()}] merge done", flush=True)
    del wave_inputs, outs, m, resolved
