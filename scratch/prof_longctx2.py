import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np, jax
from mapreduce_tpu.parallel import make_mesh
from mapreduce_tpu.models.transformer import TransformerConfig, TransformerTrainer
mesh = make_mesh()
cfg = TransformerConfig(vocab=32768, embed=1024, n_layers=8,
                        n_heads=16, head_dim=64, ffn=4096,
                        remat=True, attn_block=1024, loss_block=2048)
tr = TransformerTrainer(mesh, cfg, learning_rate=1e-3)
params = tr.init_params()
T = 65536
toks = np.random.default_rng(0).integers(0, cfg.vocab, size=(1, T + 1)).astype(np.int32)
try:
    params, loss = tr.step(params, toks); print("loss", float(loss))
except Exception as e:
    print("FAIL:", str(e)[:2000])
