"""Bisect: what makes _shard_inputs transfers slow vs bare puts?"""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P
import bench
from mapreduce_tpu.engine import DeviceWordCount, EngineConfig
from mapreduce_tpu.ops.tokenize import shard_text
from mapreduce_tpu.parallel import make_mesh

mesh = make_mesh()
sh = NamedSharding(mesh, P("data"))
MB = 1 << 20
corpus = bench.make_corpus()
chunks, L = shard_text(corpus, 94, pad_multiple=512)
print("chunks", chunks.shape, flush=True)

def t(label, fnc, reps=2):
    for r in range(reps):
        t0 = time.time(); out = fnc(); jax.block_until_ready(out)
        print(f"{label:44s} {time.time()-t0:6.2f}s", flush=True)
        del out

# 1: 8 puts of 12-row views of shard_text chunks (incl. tail handling)
def puts_shard_text():
    outs = []
    for w in range(8):
        lo = w * 12
        if lo + 12 <= 94:
            block = chunks[lo:lo + 12]
        else:
            block = np.zeros((12,) + chunks.shape[1:], chunks.dtype)
            block[:94 - lo] = chunks[lo:]
        outs.append(jax.device_put(block, sh))
    return outs
t("1: 12-row views of shard_text arr", puts_shard_text)

# 2: same rows but from a flat frombuffer reshape (prof_threads style)
flat = np.frombuffer(corpus, dtype=np.uint8)
rows = flat.size // L
c2 = flat[:rows * L].reshape(rows, L)
def puts_frombuffer():
    return [jax.device_put(c2[w * 11:(w + 1) * 11], sh) for w in range(8)]
t("2: 11-row views of frombuffer arr", puts_frombuffer)

# 3: copy of shard_text array (fresh allocation, same content)
c3 = chunks.copy()
def puts_copy():
    return [jax.device_put(c3[w * 12:(w + 1) * 12][: 94 - w * 12 if w == 7 else 12], sh) for w in range(8)]
t("3: views of chunks.copy()", puts_copy)

# 4: one put of whole chunks
t("4: single put whole chunks", lambda: jax.device_put(chunks, sh))
