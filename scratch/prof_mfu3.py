"""Train-step MFU with the Pallas flash path: per-dispatch vs scanned."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax

from mapreduce_tpu.parallel import make_mesh
from mapreduce_tpu.models.transformer import (TransformerConfig,
                                              TransformerTrainer)

PEAK = 197e12
mesh = make_mesh()
B, T = 4, 2048
S = 16  # steps per dispatch in the scanned path


def trial(name, **kw):
    cfg = TransformerConfig(vocab=32768, embed=1024, n_layers=8,
                            n_heads=16, head_dim=64, ffn=4096, **kw)
    tr = TransformerTrainer(mesh, cfg, learning_rate=1e-3)
    params = tr.init_params()
    n_params = sum(int(np.prod(np.shape(p)))
                   for p in jax.tree.leaves(params))
    attn = 3 * 2 * 2 * B * cfg.n_heads * T * T * cfg.head_dim
    flops = 6.0 * n_params * (B * T) + attn
    rng = np.random.default_rng(0)

    # single-step path
    toks = rng.integers(0, cfg.vocab, size=(B, T + 1)).astype(np.int32)
    x, y = tr.place_batch(toks)
    state = {"p": params}

    def step():
        state["p"], loss = tr._train_step(state["p"], x, y)
        return loss

    for _ in range(3):
        out = step()
    np.asarray(out).ravel()[:1]
    t0 = time.time()
    for _ in range(5):
        out = step()
    np.asarray(out).ravel()[:1]
    t5 = time.time() - t0
    t0 = time.time()
    for _ in range(20):
        out = step()
    np.asarray(out).ravel()[:1]
    t20 = time.time() - t0
    sec = (t20 - t5) / 15
    print(f"{name:22s} step   {sec*1e3:8.2f} ms  "
          f"mfu={flops/sec/PEAK*100:5.1f}%", flush=True)

    # scanned multi-step path
    toks_s = rng.integers(0, cfg.vocab, size=(S, B, T + 1)).astype(np.int32)
    xs, ys = tr.place_batch(toks_s)

    def steps():
        state["p"], losses = tr._train_steps(state["p"], xs, ys)
        return losses

    out = steps()
    np.asarray(out).ravel()[:1]
    best = 1e9
    for _ in range(3):
        t0 = time.time()
        out = steps()
        np.asarray(out).ravel()[:1]
        best = min(best, (time.time() - t0) / S)
    print(f"{name:22s} scan{S:3d} {best*1e3:8.2f} ms  "
          f"mfu={flops/best/PEAK*100:5.1f}%", flush=True)


trial("flash (pallas)")
trial("ring (jnp)", flash=False)
