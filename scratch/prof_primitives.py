"""Profile candidate primitives for the engine hot path on the real chip.

Measures, per primitive: compile time and steady-state wall per call.
Run: python scratch/prof_primitives.py [sizes...]
"""
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp


def bench(name, fn, *args, reps=5):
    t0 = time.time()
    out = jax.block_until_ready(jax.jit(fn)(*args))
    compile_s = time.time() - t0
    jfn = jax.jit(fn)
    t0 = time.time()
    for _ in range(reps):
        out = jax.block_until_ready(jfn(*args))
    per = (time.time() - t0) / reps
    print(f"{name:44s} compile {compile_s:7.2f}s   run {per*1e3:9.2f} ms")
    return per


def main():
    rng = np.random.default_rng(0)
    dev = jax.devices()[0]
    print("device:", dev, dev.platform)

    N = 1 << 21  # 2M records
    B = 1 << 21  # 2M buckets
    keys = jnp.asarray(rng.integers(0, 1 << 31, size=N, dtype=np.int32))
    keys2 = jnp.asarray(rng.integers(0, 1 << 31, size=N, dtype=np.int32))
    vals = jnp.ones((N,), jnp.int32)
    slots = jnp.asarray(rng.integers(0, B, size=N, dtype=np.int32))

    # 1. scatter-add N into B
    bench("scatter-add 2M->2M", lambda s, v: jnp.zeros((B,), jnp.int32).at[s].add(v), slots, vals)
    # 1b. smaller scatter
    Ns = 1 << 16
    bench("scatter-add 64K->2M", lambda s, v: jnp.zeros((B,), jnp.int32).at[s].add(v), slots[:Ns], vals[:Ns])
    # 2. gather N from B
    table = jnp.asarray(rng.integers(0, 100, size=B, dtype=np.int32))
    bench("gather 2M from 2M", lambda t, s: t[s], table, slots)
    # 3. sort single key
    bench("sort 2M x int32 (1 operand)", lambda k: jax.lax.sort(k), keys)
    # 4. variadic sort key + 4 payload lanes
    def vsort(k1, k2, v):
        return jax.lax.sort((k1, k2, v, v, v), num_keys=2)
    bench("sort 2M x (2 keys + 3 lanes)", vsort, keys, keys2, vals)
    # 5. sort 16M single
    keys16 = jnp.asarray(rng.integers(0, 1 << 31, size=1 << 24, dtype=np.int32))
    bench("sort 16M x int32", lambda k: jax.lax.sort(k), keys16)
    # 6. one-hot matmul histogram: ids -> [1024,1024] via segment decompose
    ids = jnp.asarray(rng.integers(0, 1 << 20, size=N, dtype=np.int32))

    def matmul_hist(ids):
        hi = ids >> 10
        lo = ids & 1023
        # tile over N to bound memory: [T, 1024] onehots
        T = 1 << 13
        def body(c, idx):
            h = jax.lax.dynamic_slice(hi, (idx * T,), (T,))
            l = jax.lax.dynamic_slice(lo, (idx * T,), (T,))
            oh = jax.nn.one_hot(h, 1024, dtype=jnp.bfloat16)
            ol = jax.nn.one_hot(l, 1024, dtype=jnp.bfloat16)
            return c + jnp.dot(oh.T, ol, preferred_element_type=jnp.float32), None
        c0 = jnp.zeros((1024, 1024), jnp.float32)
        out, _ = jax.lax.scan(body, c0, jnp.arange(N // T))
        return out
    bench("matmul-hist 2M ids -> 2^20 bins (bf16)", matmul_hist, ids)

    # 7. the tokenizer scans at 4M
    sys.path.insert(0, "/root/repo")
    from mapreduce_tpu.ops.tokenize import tokenize_hash
    chunk = jnp.asarray(rng.integers(97, 110, size=1 << 22, dtype=np.uint8))
    bench("tokenize_hash 4MB chunk", lambda c: tokenize_hash(c).keys, chunk)

    # 8. cumsum 4M (for compaction cost reference)
    x = jnp.asarray(rng.integers(0, 2, size=1 << 22, dtype=np.int32))
    bench("cumsum 4M int32", lambda a: jnp.cumsum(a), x)

    # 9. compaction via scatter: 4M -> 256K slots
    flag = x.astype(bool)
    def compact_scatter(fl, data):
        idx = jnp.cumsum(fl.astype(jnp.int32)) - 1
        idx = jnp.where(fl, idx, 1 << 18)
        return jnp.zeros((1 << 18,), jnp.int32).at[idx].set(data, mode="drop")
    bench("compact 4M->256K via scatter-set", compact_scatter, flag, x)

    # 10. top_k for compaction: 4M -> 64K
    scores = jnp.asarray(rng.integers(0, 1 << 30, size=1 << 22, dtype=np.int32))
    bench("top_k 4M -> 64K", lambda s: jax.lax.top_k(s, 1 << 16)[0], scores)


if __name__ == "__main__":
    main()
