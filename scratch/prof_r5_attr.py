"""Round-5 32K attribution: is attention MXU-geometry-bound at D=64?

Hypothesis: QK^T ([bq,64]x[64,bkv]) and PV ([bq,bkv]x[bkv,64]) both use
half the 128x128 MXU when head_dim=64, and flash bwd executes 9
tile-matmuls vs the 6 the MFU formula counts (s recomputed in both dq
and dkv passes) -> attention ceiling = 0.5 * (6/9) = 33% of causal
useful peak, which is exactly the measured 0.8s.  If true, H=8/D=128
(same E, same params, same counted FLOPs) doubles the ceiling.
"""
import sys, time, os
sys.path.insert(0, "/root/repo")
os.environ.setdefault("MAPREDUCE_TPU_CACHE", "/root/repo/.jax_cache")
import numpy as np
import jax, jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

PEAK = 197e12
B, T, E, F, V = 1, 32768, 1024, 4096, 32768

from mapreduce_tpu.ops.flash_attention import flash_attention


def slope(f, n=12):
    out = None
    for _ in range(3):
        out = f()
    np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
    t0 = time.time()
    for _ in range(n // 4):
        out = f()
    np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
    ts = time.time() - t0
    t0 = time.time()
    for _ in range(n):
        out = f()
    np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
    tb = time.time() - t0
    return (tb - ts) / (n - n // 4)


def attn_case(H, D, fwd_only=False, n_rep=8):
    q = jax.random.normal(jax.random.key(0), (B, H, T, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.key(1), (B, H, T, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.key(2), (B, H, T, D), jnp.bfloat16)

    def chain(x):
        o = x
        for _ in range(n_rep):
            o = flash_attention(o, k, v, causal=True)
        return o

    if fwd_only:
        g = jax.jit(chain)
    else:
        g = jax.jit(lambda x: jax.grad(lambda a: jnp.sum(chain(a).astype(
            jnp.float32)))(x).astype(jnp.bfloat16))
    sec = slope(lambda: g(q))
    # counted dense-equiv FLOPs (the MFU formula's convention)
    mm = 2 if fwd_only else 6
    fl = mm * n_rep * 2 * B * H * T * T * D
    useful = fl / 2  # causal
    print(f"attn H={H:3d} D={D:3d} {'fwd    ' if fwd_only else 'fwd+bwd'}"
          f" x{n_rep}: {sec*1e3:7.1f} ms  "
          f"dense {fl/sec/1e12:6.1f} TF/s  useful {useful/sec/1e12:6.1f}"
          f" TF/s ({useful/sec/PEAK*100:4.1f}% peak)", flush=True)
    return sec


for fwd_only in (True, False):
    attn_case(16, 64, fwd_only)
    attn_case(8, 128, fwd_only)

# dense part: ffn chain at 32K
xin = jax.random.normal(jax.random.key(3), (B, T, E), jnp.bfloat16)
w_in = jax.random.normal(jax.random.key(5), (E, F), jnp.bfloat16)
w_out = jax.random.normal(jax.random.key(6), (F, E), jnp.bfloat16)


def mm8(x, w_in, w_out):
    for _ in range(8):
        u = jax.nn.gelu(jnp.einsum("bte,ef->btf", x, w_in))
        x = x + jnp.einsum("btf,fe->bte", u, w_out)
    return jnp.sum(x.astype(jnp.float32))


mg = jax.jit(jax.grad(mm8, argnums=(0, 1, 2)))
sec = slope(lambda: mg(xin, w_in, w_out)[0])
fl = 6 * 8 * B * T * 2 * E * F
print(f"ffn x8 fwd+bwd:      {sec*1e3:7.1f} ms ({fl/sec/1e12:5.1f} TF/s, "
      f"{fl/sec/PEAK*100:4.1f}% peak)", flush=True)

# qkv+proj chain (E x E-ish matmuls: 4 * E*HD per layer)
wq = jax.random.normal(jax.random.key(7), (E, E), jnp.bfloat16)


def qk8(x, w):
    for _ in range(32):  # 8 layers x 4 projections
        x = x + jnp.einsum("bte,ef->btf", x, w) * 0.01
    return jnp.sum(x.astype(jnp.float32))


qg = jax.jit(jax.grad(qk8, argnums=(0, 1)))
sec = slope(lambda: qg(xin, wq)[0])
fl = 6 * 32 * B * T * E * E
print(f"proj x32 fwd+bwd:    {sec*1e3:7.1f} ms ({fl/sec/1e12:5.1f} TF/s, "
      f"{fl/sec/PEAK*100:4.1f}% peak)", flush=True)

# loss head at 32K with loss_block scan (as the model runs it)
unemb = jax.random.normal(jax.random.key(4), (E, V), jnp.bfloat16)
tgt = jnp.asarray(np.random.default_rng(0).integers(0, V, (B, T)),
                  jnp.int32)


def head(x, w, t, Tc=2048):
    C = T // Tc
    xs = jnp.moveaxis(x.reshape(B, C, Tc, E), 1, 0)
    ts = jnp.moveaxis(t.reshape(B, C, Tc), 1, 0)

    def chunk(_, xt):
        x_c, t_c = xt
        logits = jnp.einsum("bte,ev->btv", x_c, w,
                            preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        tl = jnp.take_along_axis(logits, t_c[..., None], axis=-1)[..., 0]
        return None, (lse - tl)

    body = jax.checkpoint(chunk)
    _, nll = jax.lax.scan(body, None, (xs, ts))
    return jnp.mean(nll)


hg = jax.jit(jax.grad(head, argnums=(0, 1)))
sec = slope(lambda: hg(xin, unemb, tgt)[0])
fl = 6 * B * T * E * V  # checkpointed: +2 recompute fwd -> 8/6 executed
print(f"loss head (scan):    {sec*1e3:7.1f} ms ({fl/sec/1e12:5.1f} TF/s "
      f"counted, {fl*8/6/sec/1e12:5.1f} executed, "
      f"{fl/sec/PEAK*100:4.1f}% peak)", flush=True)
