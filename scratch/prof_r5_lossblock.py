"""loss_block sweep at 32K within ONE process (cross-process chip drift
makes separate runs incomparable): does a larger cross-entropy chunk
lift the 32K step?"""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np, jax
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
from mapreduce_tpu.models.transformer import TransformerConfig, TransformerTrainer
from mapreduce_tpu.parallel import make_mesh

T = 32768
toks = np.random.default_rng(0).integers(0, 32768, (1, T + 1)).astype(np.int32)
for lb in (2048, 4096, 8192):
    cfg = TransformerConfig(vocab=32768, embed=1024, n_layers=8,
                            n_heads=8, head_dim=128, ffn=4096,
                            loss_block=lb)
    tr = TransformerTrainer(make_mesh(), cfg, learning_rate=1e-4)
    p = tr.init_params()
    p, loss = tr.step(p, toks)
    np.asarray(loss)
    best = np.inf
    for _ in range(4):
        t0 = time.time()
        for _ in range(3):
            p, loss = tr.step(p, toks)
        np.asarray(loss)
        best = min(best, (time.time() - t0) / 3)
    print(f"loss_block={lb}: {best:.3f}s/step = {T/best/1e3:.1f}k tok/s",
          flush=True)
    del p, tr
