import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np, jax
from mapreduce_tpu.parallel import make_mesh
from mapreduce_tpu.models.transformer import TransformerConfig, TransformerTrainer
mesh = make_mesh()

def try_cfg(T, layers, tag):
    cfg = TransformerConfig(vocab=32768, embed=1024, n_layers=layers,
                            n_heads=16, head_dim=64, ffn=4096,
                            remat=True, attn_block=1024, loss_block=2048)
    try:
        tr = TransformerTrainer(mesh, cfg, learning_rate=1e-3)
        params = tr.init_params()
        toks = np.random.default_rng(0).integers(
            0, cfg.vocab, size=(1, T + 1)).astype(np.int32)
        t0=time.time(); params, loss = tr.step(params, toks); lv=float(loss)
        t1=time.time(); params, loss = tr.step(params, toks); lv=float(loss)
        print(f"{tag}: OK step {time.time()-t1:.2f}s loss {lv:.2f}", flush=True)
    except Exception as e:
        print(f"{tag}: FAIL {str(e)[:120]}", flush=True)

try_cfg(49152, 8, "T=49152 L8")
try_cfg(65536, 2, "T=65536 L2")
