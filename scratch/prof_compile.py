"""Attribute the bench warmup's cold-compile time (VERDICT r3 #7).

Times .lower() (trace -> StableHLO) and .compile() (XLA/Mosaic) for each
program the bench warmup builds, at the exact bench shapes, on whatever
backend JAX_PLATFORMS selects — run once under the TPU tunnel and once
with JAX_PLATFORMS=cpu to split 'HLO is huge' from 'remote service is
slow'.
"""
import os, sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax

from mapreduce_tpu.engine import DeviceWordCount, EngineConfig
from mapreduce_tpu.engine.wordcount import shard_text
from mapreduce_tpu.parallel import make_mesh

mesh = make_mesh()
wc = DeviceWordCount(
    mesh, chunk_len=1 << 22,
    config=EngineConfig(local_capacity=1 << 18,
                        exchange_capacity=1 << 17,
                        out_capacity=1 << 18,
                        tile=512, tile_records=104))

# bench corpus: 307MB -> 6 waves; reproduce the wave shape cheaply
n_bytes = 322_000_000
n_chunks = -(-n_bytes // (1 << 22))
eng = wc._engine_for(1 << 22)
n_chunks = -(-n_chunks // eng.n_dev) * eng.n_dev
fake = np.zeros((n_chunks, 1 << 22), np.uint8)
W = eng._auto_waves(fake)
k = -(-n_chunks // (W * eng.n_dev))
print(f"chunks={n_chunks} waves={W} chunks/dev/wave={k}", flush=True)

cfg = eng.config
fn = eng._program(cfg)
chunks_shape = jax.ShapeDtypeStruct((k * eng.n_dev, 1 << 22), jnp_u8 :=
                                    np.uint8)
idx_shape = jax.ShapeDtypeStruct((k * eng.n_dev,), np.int32)
n_shape = jax.ShapeDtypeStruct((), np.int32)

t0 = time.time()
lowered = fn.lower(chunks_shape, idx_shape, n_shape)
t_lower = time.time() - t0
t0 = time.time()
lowered.compile()
t_compile = time.time() - t0
print(f"main program : lower {t_lower:.1f}s  compile {t_compile:.1f}s",
      flush=True)

merge = eng._merge_program(cfg)
C = cfg.out_capacity
P = eng.n_dev
km = jax.ShapeDtypeStruct((P, 2 * C, 2), np.uint32)
vm = jax.ShapeDtypeStruct((P, 2 * C), np.int32)
pm = jax.ShapeDtypeStruct((P, 2 * C, 2), np.int32)
am = jax.ShapeDtypeStruct((P, 2 * C), bool)
t0 = time.time()
lm = merge.lower(km, vm, pm, am)
t_lower = time.time() - t0
t0 = time.time()
lm.compile()
t_compile = time.time() - t0
print(f"merge program: lower {t_lower:.1f}s  compile {t_compile:.1f}s",
      flush=True)
