"""Attribute the 32K-context step (1.37s measured, ~0.24s ideal)."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp

PEAK = 197e12
B, T, H, D, E, F, V = 1, 32768, 16, 64, 1024, 4096, 32768

from mapreduce_tpu.ops.flash_attention import flash_attention

q = jax.random.normal(jax.random.key(0), (B, H, T, D), jnp.bfloat16)
k = jax.random.normal(jax.random.key(1), (B, H, T, D), jnp.bfloat16)
v = jax.random.normal(jax.random.key(2), (B, H, T, D), jnp.bfloat16)


def slope(f, n=12):
    out = None
    for _ in range(3):
        out = f()
    np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
    t0 = time.time()
    for _ in range(n // 4):
        out = f()
    np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
    ts = time.time() - t0
    t0 = time.time()
    for _ in range(n):
        out = f()
    np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
    tb = time.time() - t0
    return (tb - ts) / (n - n // 4)


fl_attn = 8 * 3 * 2 * 2 * B * H * T * T * D  # 8 layers, fwd+bwd


def attn8(x):
    o = x
    for _ in range(8):
        o = flash_attention(o, k, v, causal=True)
    return o


g = jax.jit(lambda x: jax.grad(lambda a: jnp.sum(attn8(a).astype(
    jnp.float32)))(x).astype(jnp.bfloat16))
sec = slope(lambda: g(q))
print(f"attn x8 fwd+bwd(dq): {sec*1e3:7.1f} ms "
      f"({fl_attn/sec/1e12:5.1f} TF/s dense-equiv; causal useful = half)",
      flush=True)

# loss head at 32K with loss_block scan
xin = jax.random.normal(jax.random.key(3), (B, T, E), jnp.bfloat16)
unemb = jax.random.normal(jax.random.key(4), (E, V), jnp.bfloat16)
tgt = jnp.asarray(np.random.default_rng(0).integers(0, V, (B, T)),
                  jnp.int32)


def head(x, w, t, Tc=2048):
    C = T // Tc
    xs = jnp.moveaxis(x.reshape(B, C, Tc, E), 1, 0)
    ts = jnp.moveaxis(t.reshape(B, C, Tc), 1, 0)

    def chunk(_, xt):
        x_c, t_c = xt
        logits = jnp.einsum("bte,ev->btv", x_c, w,
                            preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        tl = jnp.take_along_axis(logits, t_c[..., None], axis=-1)[..., 0]
        return None, (lse - tl)

    body = jax.checkpoint(chunk)
    _, nll = jax.lax.scan(body, None, (xs, ts))
    return jnp.mean(nll)


hg = jax.jit(jax.grad(head, argnums=(0, 1)))
sec = slope(lambda: hg(xin, unemb, tgt)[0])
print(f"loss head (scan):    {sec*1e3:7.1f} ms "
      f"({6*B*T*E*V/sec/1e12:5.1f} TF/s)", flush=True)

# ffn/qkv matmul chain at 32K
w_in = jax.random.normal(jax.random.key(5), (E, F), jnp.bfloat16)
w_out = jax.random.normal(jax.random.key(6), (F, E), jnp.bfloat16)


def mm(x, w_in, w_out):
    for _ in range(8):
        u = jax.nn.gelu(jnp.einsum("bte,ef->btf", x, w_in))
        x = x + jnp.einsum("btf,fe->bte", u, w_out)
    return jnp.sum(x.astype(jnp.float32))


mg = jax.jit(jax.grad(mm, argnums=(0, 1, 2)))
sec = slope(lambda: mg(xin, w_in, w_out)[0])
print(f"ffn x8 fwd+bwd:      {sec*1e3:7.1f} ms "
      f"({6*8*B*T*2*E*F/sec/1e12:5.1f} TF/s)", flush=True)
