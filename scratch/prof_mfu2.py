"""Full-step MFU under attention/loss chunking variants (bench config)."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax

from mapreduce_tpu.parallel import make_mesh
from mapreduce_tpu.models.transformer import (
    TransformerConfig, TransformerTrainer)

PEAK = 197e12
mesh = make_mesh()
B, T = 4, 2048 * mesh.shape["data"]


def _run(step, n):
    out = None
    for _ in range(n):
        out = step()
    np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]


def slope(step, n=20):
    _run(step, 3)
    t0 = time.time(); _run(step, n // 4); t_small = time.time() - t0
    t0 = time.time(); _run(step, n); t_big = time.time() - t0
    return (t_big - t_small) / (n - n // 4)


def trial(name, **kw):
    cfg = TransformerConfig(vocab=32768, embed=1024, n_layers=8,
                            n_heads=16, head_dim=64, ffn=4096, **kw)
    tr = TransformerTrainer(mesh, cfg, learning_rate=1e-3)
    params = tr.init_params()
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab, size=(B, T + 1)).astype(np.int32)
    x, y = tr.place_batch(toks)
    state = {"p": params}

    def step():
        state["p"], loss = tr._train_step(state["p"], x, y)
        return loss

    sec = slope(step)
    n_params = sum(int(np.prod(np.shape(p)))
                   for p in jax.tree.leaves(state["p"]))
    attn = 3 * 2 * 2 * B * cfg.n_heads * T * T * cfg.head_dim
    flops = 6.0 * n_params * (B * T) + attn
    print(f"{name:28s} {sec*1e3:8.2f} ms  mfu={flops/sec/PEAK*100:5.1f}%",
          flush=True)


trial("baseline (no chunking)")
trial("attn_block=1024", attn_block=1024)
trial("attn_block=512", attn_block=512)
trial("attn_block=256", attn_block=256)
trial("loss_block=1024", loss_block=1024)
trial("attn1024+loss1024", attn_block=1024, loss_block=1024)
trial("attn512+remat", attn_block=512, remat=True)
