"""Serialize the wave pipeline to attribute time: per-wave upload (blocked),
per-wave compute (blocked), merge."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
import jax.numpy as jnp

import bench
from mapreduce_tpu.engine import DeviceWordCount, EngineConfig
from mapreduce_tpu.ops.tokenize import shard_text
from mapreduce_tpu.parallel import make_mesh

corpus = bench.make_corpus()
mesh = make_mesh()
wc = DeviceWordCount(mesh, chunk_len=1 << 22,
                     config=EngineConfig(local_capacity=1 << 18,
                                         exchange_capacity=1 << 17,
                                         out_capacity=1 << 18))
n_chunks = -(-len(corpus) // wc.chunk_len)
chunks, L = shard_text(corpus, n_chunks, pad_multiple=wc.config.tile)
print("chunks", chunks.shape, flush=True)
eng = wc._engine_for(L)
cfg = eng.config
fn = eng._get_compiled(cfg)

W = 8
wave_inputs, n_real = eng._shard_inputs(chunks, W)
jax.block_until_ready([c for c, _ in wave_inputs])
print("all inputs resident (warm cache?)", flush=True)

# warm compile
out = fn(*wave_inputs[0], n_real)
jax.block_until_ready(out[4])
print("compiled", flush=True)

# serialized timing, fresh inputs
del wave_inputs, out
for trial in range(2):
    t_all = time.time()
    wave_inputs, n_real = eng._shard_inputs(chunks, W)
    up = cp = 0.0
    outs = []
    for ci, ii in wave_inputs:
        t0 = time.time(); jax.block_until_ready(ci); up += time.time() - t0
        t0 = time.time(); o = fn(ci, ii, n_real)
        jax.block_until_ready(o[4]); cp += time.time() - t0
        outs.append(o)
    merge = eng._get_merge(cfg)
    cat = lambda i: jnp.concatenate([o[i] for o in outs], axis=1)
    t0 = time.time()
    m = merge(cat(0), cat(1), cat(2), cat(3))
    jax.block_until_ready(m[0]); mg = time.time() - t0
    print(f"trial{trial}: upload {up:.2f}s compute {cp:.2f}s merge {mg:.2f}s "
          f"wall {time.time()-t_all:.2f}s", flush=True)
    del wave_inputs, outs, m
