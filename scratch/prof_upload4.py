"""Reproduce the bench's degrading upload: call _shard_inputs repeatedly
on the real 393MB chunk batch, and compare against a plain sharded
device_put of the same array."""
import time
import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

import os, sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench
from mapreduce_tpu.engine import DeviceWordCount, EngineConfig
from mapreduce_tpu.ops.tokenize import shard_text
from mapreduce_tpu.parallel import make_mesh

corpus = bench.make_corpus()
mesh = make_mesh()
wc = DeviceWordCount(mesh, chunk_len=1 << 22,
                     config=EngineConfig(local_capacity=1 << 18,
                                         exchange_capacity=1 << 17,
                                         out_capacity=1 << 18))
n_chunks = max(1, -(-len(corpus) // wc.chunk_len))
n_dev = mesh.shape["data"]
n_chunks = -(-n_chunks // n_dev) * n_dev
chunks, L = shard_text(corpus, n_chunks, pad_multiple=wc.config.tile)
print("chunks", chunks.shape, chunks.nbytes / 1e6, "MB", flush=True)
eng = wc._engine_for(L)

for i in range(4):
    t0 = time.time()
    a, b, c = eng._shard_inputs(chunks)
    jax.block_until_ready(a)
    print(f"_shard_inputs {i}: {time.time()-t0:6.2f}s", flush=True)
    del a, b

sh = NamedSharding(mesh, P("data"))
for i in range(3):
    t0 = time.time()
    a = jax.device_put(chunks, sh)
    jax.block_until_ready(a)
    print(f"plain sharded device_put {i}: {time.time()-t0:6.2f}s", flush=True)
    del a
