"""Is the pre-program 'fast put' a deferred transfer? Time a compute that
consumes the uploaded data, with value readback."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np, jax, jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
import bench
from mapreduce_tpu.ops.tokenize import shard_text
from mapreduce_tpu.parallel import make_mesh

mesh = make_mesh()
sh = NamedSharding(mesh, P("data"))
corpus = bench.make_corpus()
chunks, L = shard_text(corpus, 94, pad_multiple=512)

t0 = time.time()
dev = jax.device_put(chunks, sh)
jax.block_until_ready(dev)
print(f"put claims ready in {time.time()-t0:.2f}s", flush=True)

f = jax.jit(lambda x: x.astype(jnp.int32).sum())
# warm compile on tiny data to exclude compile from timing
_ = np.asarray(f(jnp.ones((2, 8), jnp.uint8)))
t0 = time.time()
s = int(np.asarray(f(dev)))
print(f"consuming compute (sum) took {time.time()-t0:.2f}s -> {s}", flush=True)
t0 = time.time()
s = int(np.asarray(f(dev)))
print(f"second consume {time.time()-t0:.2f}s", flush=True)
