"""Smoke: does a Pallas kernel run on the axon platform, and what does
the shipped TPU flash attention achieve at bench shapes (feasibility
ceiling for an in-tree kernel)?"""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp

B, T, H, D = 4, 2048, 16, 64
q = jax.random.normal(jax.random.key(0), (B, H, T, D), jnp.bfloat16)
k = jax.random.normal(jax.random.key(1), (B, H, T, D), jnp.bfloat16)
v = jax.random.normal(jax.random.key(2), (B, H, T, D), jnp.bfloat16)

from jax.experimental.pallas.ops.tpu import flash_attention as fa


def loss(q, k, v):
    o = fa.flash_attention(q, k, v, causal=True, sm_scale=D ** -0.5)
    return jnp.sum(o.astype(jnp.float32))


g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))


def run(n):
    out = None
    for _ in range(n):
        out = g(q, k, v)
    np.asarray(out[0]).ravel()[:1]


run(3)
t0 = time.time(); run(5); ts = time.time() - t0
t0 = time.time(); run(20); tb = time.time() - t0
sec = (tb - ts) / 15
flops = 3 * 2 * 2 * B * H * T * T * D  # fwd+bwd, 2 matmuls (causal: /2 work)
print(f"shipped flash fwd+bwd (1 layer): {sec*1e3:.2f} ms  "
      f"({flops/sec/1e12:.1f} TF/s dense-equiv)", flush=True)
