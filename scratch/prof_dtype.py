"""In a post-engine-run (degraded) process: uint8 vs int32-view puts."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P
import bench
from mapreduce_tpu.engine import DeviceWordCount, EngineConfig
from mapreduce_tpu.ops.tokenize import shard_text
from mapreduce_tpu.parallel import make_mesh

mesh = make_mesh()
sh = NamedSharding(mesh, P("data"))
corpus = bench.make_corpus()
chunks, L = shard_text(corpus, 94, pad_multiple=512)
wc = DeviceWordCount(mesh, chunk_len=1 << 22,
                     config=EngineConfig(local_capacity=1 << 18,
                                         exchange_capacity=1 << 17,
                                         out_capacity=1 << 18))
eng = wc._engine_for(L)
fn = eng._get_compiled(eng.config)
out = fn(jax.device_put(chunks, sh),
         jax.device_put(np.arange(94, dtype=np.int32), sh), np.int32(94))
jax.block_until_ready(out[4]); del out
print("engine ran (process now in degraded-transfer regime)", flush=True)

c32 = chunks.view(np.int32)
c16 = chunks.view(np.uint16)
for rep in range(3):
    t0 = time.time(); o = jax.device_put(chunks, sh); jax.block_until_ready(o); del o
    print(f"rep{rep} uint8  {time.time()-t0:6.2f}s", flush=True)
    t0 = time.time(); o = jax.device_put(c32, sh); jax.block_until_ready(o); del o
    print(f"rep{rep} int32  {time.time()-t0:6.2f}s", flush=True)
    t0 = time.time(); o = jax.device_put(c16, sh); jax.block_until_ready(o); del o
    print(f"rep{rep} uint16 {time.time()-t0:6.2f}s", flush=True)
