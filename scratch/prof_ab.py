"""A/B at the same instant: fresh random vs fresh text vs repeated content."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P
from mapreduce_tpu.parallel import make_mesh
import bench

mesh = make_mesh()
sh = NamedSharding(mesh, P("data"))
MB = 1 << 20

corpus = bench.make_corpus(13_000_000, 480_000)
text = np.frombuffer(corpus, dtype=np.uint8)[:96 * MB].reshape(24, 4 * MB)

def put(arr, label):
    t0 = time.time()
    out = jax.device_put(arr, sh)
    jax.block_until_ready(out)
    dt = time.time() - t0
    print(f"{label:40s} {dt:6.2f}s {arr.nbytes/MB/dt:7.1f} MB/s", flush=True)
    del out

for rep in range(3):
    rnd = np.random.default_rng(None).integers(0, 255, size=(24, 4 * MB),
                                               dtype=np.uint8)
    put(rnd, f"rep{rep} fresh random 96MB")
    put(text, f"rep{rep} same text 96MB")
    t2 = (text.astype(np.int16) + rep + 1).astype(np.uint8)  # new content
    put(t2, f"rep{rep} perturbed text 96MB")
    zeros = np.zeros((24, 4 * MB), np.uint8)
    put(zeros, f"rep{rep} zeros 96MB")
