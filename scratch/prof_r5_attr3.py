"""Round-5 32K attribution, big-N edition: one scan dispatch with N large
enough that dispatch+readback noise (the tunnel's ±100s of ms) is <2%.
No slope subtraction — prof_r5_attr2.py showed run-to-run variance beats
the slope at these chain lengths (negative ms/iter)."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

PEAK = 197e12
B, T, E, F, V = 1, 32768, 1024, 4096, 32768

from mapreduce_tpu.ops.flash_attention import flash_attention


def timed(make_step, x0, n, what, fl, useful_frac=1.0):
    @jax.jit
    def prog(x):
        def body(c, _):
            return make_step(c), None
        y, _ = jax.lax.scan(body, x, None, length=n)
        return y

    r = prog(x0)
    np.asarray(jax.tree.leaves(r)[0]).ravel()[:1]
    best = np.inf
    for _ in range(4):
        t0 = time.time()
        r = prog(x0)
        np.asarray(jax.tree.leaves(r)[0]).ravel()[:1]
        best = min(best, time.time() - t0)
    sec = best / n
    useful = fl * useful_frac
    print(f"{what:26s}: {sec*1e3:8.2f} ms/iter (n={n}, wall {best:6.2f}s) "
          f"dense {fl/sec/1e12:6.1f} TF/s  useful {useful/sec/1e12:6.1f}"
          f" TF/s ({useful/sec/PEAK*100:5.1f}% peak)", flush=True)
    return sec


def attn(H, D, train, n):
    k = jax.random.normal(jax.random.key(1), (B, H, T, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.key(2), (B, H, T, D), jnp.bfloat16)
    q = jax.random.normal(jax.random.key(0), (B, H, T, D), jnp.bfloat16)

    if train:
        def loss(x):
            return jnp.sum(flash_attention(x, k, v, causal=True
                                           ).astype(jnp.float32))

        def step(x):
            return (x - 1e-3 * jax.grad(loss)(x)).astype(jnp.bfloat16)
        fl = 6 * 2 * B * H * T * T * D
    else:
        def step(x):
            return flash_attention(x, k, v, causal=True)
        fl = 2 * 2 * B * H * T * T * D
    timed(step, q, n,
          f"attn {'f+b' if train else 'fwd'} H={H} D={D}", fl, 0.5)


attn(8, 128, False, 192)
attn(8, 128, True, 64)
attn(16, 64, False, 24)
attn(16, 64, True, 24)

xin = jax.random.normal(jax.random.key(3), (B, T, E), jnp.bfloat16)
w_in = jax.random.normal(jax.random.key(5), (E, F), jnp.bfloat16)
w_out = jax.random.normal(jax.random.key(6), (F, E), jnp.bfloat16)


def ffn_loss(x):
    u = jax.nn.gelu(jnp.einsum("bte,ef->btf", x, w_in))
    return jnp.sum((x + jnp.einsum("btf,fe->bte", u, w_out)
                    ).astype(jnp.float32))


def ffn_step(x):
    return (x - 1e-3 * jax.grad(ffn_loss)(x)).astype(jnp.bfloat16)


timed(ffn_step, xin, 128, "ffn f+b", 6 * B * T * 2 * E * F)

unemb = jax.random.normal(jax.random.key(4), (E, V), jnp.bfloat16)
tgt = jnp.asarray(np.random.default_rng(0).integers(0, V, (B, T)),
                  jnp.int32)


def head_loss(x, Tc=2048):
    C = T // Tc
    xs = jnp.moveaxis(x.reshape(B, C, Tc, E), 1, 0)
    ts = jnp.moveaxis(tgt.reshape(B, C, Tc), 1, 0)

    def chunk(_, xt):
        x_c, t_c = xt
        logits = jnp.einsum("bte,ev->btv", x_c, unemb,
                            preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        tl = jnp.take_along_axis(logits, t_c[..., None], axis=-1)[..., 0]
        return None, (lse - tl)

    _, nll = jax.lax.scan(jax.checkpoint(chunk), None, (xs, ts))
    return jnp.mean(nll)


def head_step(x):
    return (x - 1e-3 * jax.grad(head_loss)(x)).astype(jnp.bfloat16)


timed(head_step, xin, 48, "loss head f+b", 6 * B * T * E * V)
