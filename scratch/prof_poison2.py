"""Does the degraded-transfer regime recover in-process? Is it deletion-
driven? Sequence: put / engine / puts with sleeps / puts holding buffers."""
import sys, time, gc
sys.path.insert(0, "/root/repo")
import numpy as np, jax
from jax.sharding import NamedSharding, PartitionSpec as P
import bench
from mapreduce_tpu.engine import DeviceWordCount, EngineConfig
from mapreduce_tpu.ops.tokenize import shard_text
from mapreduce_tpu.parallel import make_mesh

mesh = make_mesh()
sh = NamedSharding(mesh, P("data"))
corpus = bench.make_corpus()
chunks, L = shard_text(corpus, 94, pad_multiple=512)

def put(tag, hold=[]):
    t0 = time.time()
    out = jax.device_put(chunks, sh)
    jax.block_until_ready(out)
    dt = time.time() - t0
    print(f"{tag:34s} {dt:6.2f}s {chunks.nbytes/1e6/dt:7.0f} MB/s", flush=True)
    return out

x = put("1 pre-engine put"); del x
wc = DeviceWordCount(mesh, chunk_len=1 << 22,
                     config=EngineConfig(local_capacity=1 << 18,
                                         exchange_capacity=1 << 17,
                                         out_capacity=1 << 18))
eng = wc._engine_for(L)
fn = eng._get_compiled(eng.config)
dev = jax.device_put(chunks, sh)
out = fn(dev, jax.device_put(np.arange(94, dtype=np.int32), sh), np.int32(94))
jax.block_until_ready(out[4])
print("engine ran", flush=True)

# keep EVERYTHING alive (no deletions possible)
x1 = put("2 post-engine put (outputs alive)")
x2 = put("3 again (all alive)")
del out, dev  # now release the engine buffers
gc.collect()
x3 = put("4 after deleting engine buffers")
for i in range(4):
    time.sleep(5)
    x = put(f"5.{i} after {5*(i+1)}s sleep"); del x
