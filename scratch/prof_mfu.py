"""MFU attribution for the bench transformer config (VERDICT r3 #2).

Times each component of the 168M-param / T=2048 train step in isolation
(jitted, chained executes, value-readback drain) and reports achieved
FLOP/s per component vs the v5e peak, so the missing MFU is attributed
rather than guessed.

Components:
  full        the real _train_step (fwd+bwd+SGD)
  fwd         loss only (no grad)
  attn        8x ring_attention at bench shapes, fwd+bwd
  attn_plain  8x plain softmax attention (no ring machinery), fwd+bwd
  qkv_mm      the 8 qkv+wo+ffn matmul chains alone, fwd+bwd
  loss        unembed matmul + sharded softmax xent alone, fwd+bwd
  sgd         tree-map SGD update alone
"""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from mapreduce_tpu.parallel import make_mesh
from mapreduce_tpu.models.transformer import (
    TransformerConfig, TransformerTrainer, init_transformer,
    transformer_param_spec, loss_local)
from mapreduce_tpu.parallel.ring import ring_attention

PEAK = 197e12

mesh = make_mesh()
n_model = mesh.shape["model"]
n_chips = len(mesh.devices.flat)
cfg = TransformerConfig(vocab=32768, embed=1024, n_layers=8,
                        n_heads=16, head_dim=64, ffn=4096)
B, T = 4, 2048 * mesh.shape["data"]
E, H, D, F, V = cfg.embed, cfg.n_heads, cfg.head_dim, cfg.ffn, cfg.vocab


def _run(fn, args, n):
    out = None
    for _ in range(n):
        out = fn(*args)
    # drain: value readback of one leaf forces the whole chain
    np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
    return out


def timeit(fn, *args, n=20, warmup=3):
    """Slope timing: t(n) - t(n/4) over 3n/4 steps cancels the constant
    readback/dispatch cost the tunnel adds to any single measurement."""
    _run(fn, args, warmup)
    t0 = time.time()
    _run(fn, args, n // 4)
    t_small = time.time() - t0
    t0 = time.time()
    _run(fn, args, n)
    t_big = time.time() - t0
    return (t_big - t_small) / (n - n // 4)


def report(name, sec, flops):
    eff = flops / sec / (PEAK * n_chips)
    print(f"{name:12s} {sec*1e3:8.2f} ms  {flops/1e9:10.1f} GF "
          f"-> {eff*100:5.1f}% of peak", flush=True)


tr = TransformerTrainer(mesh, cfg, learning_rate=1e-3)
params = tr.init_params()
rng = np.random.default_rng(0)
toks = rng.integers(0, V, size=(B, T + 1)).astype(np.int32)
x, y = tr.place_batch(toks)

n_params = sum(int(np.prod(np.shape(p))) for p in jax.tree.leaves(params))
attn_flops = 3 * 2 * 2 * B * H * T * T * D
full_flops = 6.0 * n_params * (B * T) + attn_flops

state = {"p": params}


def full():
    state["p"], loss = tr._train_step(state["p"], x, y)
    return loss


sec = timeit(full)
report("full", sec, full_flops)

# ---- forward only ----
fwd = jax.jit(tr._loss)
sec = timeit(lambda: fwd(state["p"], x, y))
report("fwd", sec, full_flops / 3)

# ---- attention alone (ring, at bench shapes, fwd+bwd x n_layers) ----
kq = jax.random.normal(jax.random.key(1), (B, T, H, D), jnp.bfloat16)


def attn_loss(q, k, v):
    def local(q, k, v):
        return ring_attention(q, k, v, "data", causal=True,
                              block_size=cfg.attn_block)
    sm = jax.shard_map(local, mesh=mesh,
                       in_specs=(P(None, "data"),) * 3,
                       out_specs=P(None, "data"))
    o = q
    for _ in range(cfg.n_layers):
        o = sm(o, k, v)
    return jnp.sum(o.astype(jnp.float32))


attn_g = jax.jit(jax.grad(attn_loss))
sec = timeit(lambda: attn_g(kq, kq, kq))
report("attn_ring", sec, cfg.n_layers * 3 * 2 * 2 * B * H * T * T * D)


def attn_plain_loss(q, k, v):
    mask = jnp.tril(jnp.ones((T, T), bool))
    o = q
    for _ in range(cfg.n_layers):
        s = jnp.einsum("bqhd,bkhd->bhqk", o, k,
                       preferred_element_type=jnp.float32)
        s = jnp.where(mask[None, None], s * (D ** -0.5), -jnp.inf)
        w = jax.nn.softmax(s, axis=-1).astype(jnp.bfloat16)
        o = jnp.einsum("bhqk,bkhd->bqhd", w, v,
                       preferred_element_type=jnp.float32).astype(jnp.bfloat16)
    return jnp.sum(o.astype(jnp.float32))


if n_chips == 1:
    attn_pg = jax.jit(jax.grad(attn_plain_loss))
    sec = timeit(lambda: attn_pg(kq, kq, kq))
    report("attn_plain", sec, cfg.n_layers * 3 * 2 * 2 * B * H * T * T * D)

# ---- the matmul chain alone (qkv, wo, ffn in/out) x n_layers ----
wqkv = jax.random.normal(jax.random.key(2), (E, 3, H * D), jnp.bfloat16)
wo = jax.random.normal(jax.random.key(3), (H * D, E), jnp.bfloat16)
w_in = jax.random.normal(jax.random.key(4), (E, F), jnp.bfloat16)
w_out = jax.random.normal(jax.random.key(5), (F, E), jnp.bfloat16)
xin = jax.random.normal(jax.random.key(6), (B, T, E), jnp.bfloat16)


def mm_loss(x, wqkv, wo, w_in, w_out):
    for _ in range(cfg.n_layers):
        qkv = jnp.einsum("bte,ecf->btcf", x, wqkv)
        a = qkv[:, :, 0] + qkv[:, :, 1] + qkv[:, :, 2]
        x = x + jnp.einsum("btf,fe->bte", a, wo)
        u = jax.nn.gelu(jnp.einsum("bte,ef->btf", x, w_in))
        x = x + jnp.einsum("btf,fe->bte", u, w_out)
    return jnp.sum(x.astype(jnp.float32))


# grad wrt ALL args: grad-wrt-x-only let XLA drop the weight-gradient
# matmuls entirely (first measurement read an impossible 199% of peak)
mm_g = jax.jit(jax.grad(mm_loss, argnums=(0, 1, 2, 3, 4)))
sec = timeit(lambda: mm_g(xin, wqkv, wo, w_in, w_out)[0])
mm_flops = 6 * cfg.n_layers * B * T * (E * 3 * H * D + H * D * E + 2 * E * F)
report("mm_chain", sec, mm_flops)

# ---- loss head alone ----
unemb = jax.random.normal(jax.random.key(7), (E, V), jnp.bfloat16)


def head_loss(x, w, t):
    logits = jnp.einsum("bte,ev->btv", x, w,
                        preferred_element_type=jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    tl = jnp.take_along_axis(logits, t[..., None], axis=-1)[..., 0]
    return jnp.mean(lse - tl)


head_g = jax.jit(jax.grad(head_loss))
yh = jnp.asarray(np.asarray(y))
sec = timeit(lambda: head_g(xin, unemb, yh))
report("loss_head", sec, 6 * B * T * E * V)

# ---- SGD update alone ----
def sgd(p):
    return jax.tree.map(lambda a: a - 1e-3 * a, p)


sgd_j = jax.jit(sgd)
sec = timeit(lambda: sgd_j(state["p"]))
report("sgd", sec, 0.0)

print(f"\nn_params={n_params/1e6:.1f}M  full_flops={full_flops/1e12:.2f} TF "
      f"ideal_step={full_flops/(PEAK*n_chips)*1e3:.1f} ms", flush=True)
