"""Full-scale bench with per-stage timings to find the real bottleneck."""
import json, os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench
import jax
jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.dirname(os.path.dirname(
                      os.path.abspath(__file__))), ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
from mapreduce_tpu.engine import DeviceWordCount, EngineConfig
from mapreduce_tpu.parallel import make_mesh

t0 = time.time()
corpus = bench.make_corpus()
print(f"corpus gen {time.time()-t0:.1f}s, {len(corpus)/1e6:.0f} MB")

mesh = make_mesh()
wc = DeviceWordCount(mesh, chunk_len=1 << 22,
                     config=EngineConfig(local_capacity=1 << 18,
                                         exchange_capacity=1 << 17,
                                         out_capacity=1 << 18))
t0 = time.time()
tm = {}
counts = wc.count_bytes(corpus, timings=tm)
print(f"warmup total {time.time()-t0:.1f}s timings={tm}")
for rep in range(2):
    t0 = time.time()
    tm = {}
    counts = wc.count_bytes(corpus, timings=tm)
    print(f"run{rep} total {time.time()-t0:.2f}s timings={tm}")
print(len(counts), "uniques", sum(counts.values()), "total")
