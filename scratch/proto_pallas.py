"""Prototype: interpret-mode Pallas segmented-reduce + tokenize kernels."""
import functools
import numpy as np
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

S = np.uint32(0xFFFFFFFF)
L = 128


def _shift1_flat(x, carry):
    """x shifted right by one in flattened [R, L] order; carry fills [0,0]."""
    lastcol = x[:, -1:]                       # [R, 1]
    prevrow_last = jnp.concatenate(
        [jnp.full((1, 1), carry, x.dtype), lastcol[:-1]], axis=0)  # [R, 1]
    return jnp.concatenate([prevrow_last, x[:, :-1]], axis=1)


def _seg_ladder_lanes(flags, v, op):
    """Within-row inclusive segmented scan along the LAST axis: returns
    (seen, v) where seen[r, l] = a flag exists in row r at or before l and
    v[r, l] = op-fold of row r's elements from max(last flag, row start)
    through l.  Classic Hillis-Steele with a positional guard so unflagged
    row starts stay exact (no op-identity needed)."""
    lanes = v.shape[-1]
    lane = jax.lax.broadcasted_iota(jnp.int32, flags.shape, flags.ndim - 1)
    f = flags
    seen = flags
    d = 1
    while d < lanes:
        f_l = jnp.concatenate(
            [jnp.ones(f.shape[:-1] + (d,), bool), f[..., :-d]], axis=-1)
        v_l = jnp.concatenate([v[..., :d], v[..., :-d]], axis=-1)
        take = f | (lane < d)
        v = jnp.where(take, v, op(v_l, v))
        f = f | f_l
        seen = seen | jnp.concatenate(
            [jnp.zeros(seen.shape[:-1] + (d,), bool), seen[..., :-d]],
            axis=-1)
        d *= 2
    return seen, v


def _seg_kernel(k1_ref, k2_ref, nk1_ref, nk2_ref, v_ref,
                red_ref, csum_ref, ck_ref, cv_ref, cc_ref, *, op, R):
    b = pl.program_id(0)

    @pl.when(b == 0)
    def _init():
        ck_ref[0] = S
        ck_ref[1] = S
        cv_ref[...] = jnp.zeros_like(cv_ref)
        cc_ref[0] = jnp.int32(0)

    k1 = k1_ref[...]
    k2 = k2_ref[...]
    valid = jnp.logical_not((k1 == S) & (k2 == S))
    pk1 = _shift1_flat(k1, ck_ref[0])
    pk2 = _shift1_flat(k2, ck_ref[1])
    is_start = valid & ((k1 != pk1) | (k2 != pk2))
    nk1 = nk1_ref[...]
    nk2 = nk2_ref[...]
    nvalid = jnp.logical_not((nk1 == S) & (nk2 == S))
    is_end = valid & ((k1 != nk1) | (k2 != nk2) | jnp.logical_not(nvalid))

    # within-row segmented scan, then compose rows + block carry
    v = v_ref[...]  # [R, L]
    seen, v = _seg_ladder_lanes(is_start, v, op)
    rf = jnp.any(is_start, axis=-1)   # [R] row has a head
    rv = v[:, -1]                      # [R] row fold (from last head)
    rseen, rv_inc = _seg_ladder_lanes(rf[None, :], rv[None, :], op)
    rseen, rv_inc = rseen[0], rv_inc[0]
    carry_v = cv_ref[0, 0]
    comb = jnp.where(rseen, rv_inc,
                     op(jnp.broadcast_to(carry_v, rv_inc.shape), rv_inc))
    pv = jnp.concatenate(
        [jnp.broadcast_to(carry_v, (1,)).astype(rv.dtype), comb[:-1]])
    final = jnp.where(seen, v,
                      op(jnp.broadcast_to(pv[:, None], v.shape), v))
    red_ref[...] = final

    # plain cumsum of is_end in flattened order (+ block carry)
    e = is_end.astype(jnp.int32)
    d = 1
    while d < L:
        e = e + jnp.concatenate(
            [jnp.zeros(e.shape[:-1] + (d,), jnp.int32), e[:, :-d]], axis=1)
        d *= 2
    rt = e[:, -1]
    d = 1
    while d < R:
        rt = rt + jnp.concatenate([jnp.zeros((d,), jnp.int32), rt[:-d]])
        d *= 2
    pe = jnp.concatenate([jnp.zeros((1,), jnp.int32), rt[:-1]]) + cc_ref[0]
    csum = e + pe[:, None]
    csum_ref[...] = csum
    # carries
    ck_ref[0] = k1[R - 1, L - 1]
    ck_ref[1] = k2[R - 1, L - 1]
    cv_ref[0, 0] = final[R - 1, L - 1]
    cc_ref[0] = csum[R - 1, L - 1]


def seg_reduce_pallas(k1s, k2s, v, op, block=1024):
    N = k1s.shape[0]
    R = block // L
    npad = -(-N // block) * block
    pad = npad - N

    def padded(x, fill):
        return jnp.concatenate(
            [x, jnp.full((pad,), fill, x.dtype)]) if pad else x

    k1p = padded(k1s, S)
    k2p = padded(k2s, S)
    nk1 = jnp.concatenate([k1p[1:], jnp.full((1,), S, jnp.uint32)])
    nk2 = jnp.concatenate([k2p[1:], jnp.full((1,), S, jnp.uint32)])
    vp = padded(v, jnp.zeros((), v.dtype))
    rows = npad // L
    shape2 = (rows, L)
    args = [a.reshape(shape2) for a in (k1p, k2p, nk1, nk2, vp)]
    grid = (npad // block,)
    spec = pl.BlockSpec((R, L), lambda i: (i, 0))
    red, csum = pl.pallas_call(
        functools.partial(_seg_kernel, op=op, R=R),
        grid=grid,
        in_specs=[spec] * 5,
        out_specs=[spec, spec],
        out_shape=[jax.ShapeDtypeStruct(shape2, v.dtype),
                   jax.ShapeDtypeStruct(shape2, jnp.int32)],
        scratch_shapes=[pltpu.SMEM((2,), jnp.uint32),
                        pltpu.VMEM((1, 1), v.dtype),
                        pltpu.SMEM((1,), jnp.int32)],
        interpret=True,
    )(*args)
    return red.reshape(-1)[:N], csum.reshape(-1)[:N]


def lax_reference(k1s, k2s, v, op):
    import sys
    sys.path.insert(0, "/root/repo")
    from mapreduce_tpu.ops.segscan import segmented_scan, ladder_cumsum, _shift_right
    row_valid = ~((k1s == S) & (k2s == S))
    prev1 = _shift_right(k1s, 1, 0)
    prev2 = _shift_right(k2s, 1, 0)
    is_start = row_valid & ((k1s != prev1) | (k2s != prev2))
    is_start = is_start.at[0].set(row_valid[0])
    next1 = jnp.concatenate([k1s[1:], jnp.zeros((1,), jnp.uint32)])
    next2 = jnp.concatenate([k2s[1:], jnp.zeros((1,), jnp.uint32)])
    is_end = row_valid & ((k1s != next1) | (k2s != next2)
                          | ~jnp.concatenate([row_valid[1:],
                                              jnp.zeros((1,), bool)]))
    is_end = is_end.at[-1].set(row_valid[-1])
    scanned = segmented_scan(op, is_start, v)
    csum = ladder_cumsum(is_end.astype(jnp.int32))
    return scanned, csum, is_end


rng = np.random.default_rng(0)
for N in (1000, 4096, 5000, 1, 130, 2048):
    keys = np.sort(rng.integers(0, max(N // 7, 2), size=N).astype(np.uint32))
    k2 = (keys * 7 % 5).astype(np.uint32)
    nvalid = rng.integers(0, max(N // 3, 1))
    k1s = np.concatenate([keys[:N - nvalid],
                          np.full(nvalid, 0xFFFFFFFF, np.uint32)])
    k2s = np.concatenate([k2[:N - nvalid],
                          np.full(nvalid, 0xFFFFFFFF, np.uint32)])
    order = np.lexsort((k2s, k1s))
    k1s, k2s = k1s[order], k2s[order]
    v = rng.integers(0, 100, size=N).astype(np.int32)
    for op, name in ((jnp.add, "sum"), (jnp.minimum, "min"),
                     (jnp.maximum, "max")):
        got_r, got_c = seg_reduce_pallas(jnp.asarray(k1s), jnp.asarray(k2s),
                                         jnp.asarray(v), op)
        exp_r, exp_c, is_end = lax_reference(
            jnp.asarray(k1s), jnp.asarray(k2s), jnp.asarray(v), op)
        ie = np.asarray(is_end)
        assert np.array_equal(np.asarray(got_r)[ie],
                              np.asarray(exp_r)[ie]), (N, name)
        assert np.array_equal(np.asarray(got_c), np.asarray(exp_c)), (N, name)
    print(f"N={N} OK  ends={ie.sum()}")
print("seg kernel prototype OK")
