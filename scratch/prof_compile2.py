"""Which op dominates XLA compile time in the engine programs? (CPU —
compile cost measured identical to TPU, scratch/prof_compile.py)."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp

from mapreduce_tpu.ops.segscan import (ladder_cumsum, ladder_cummax,
                                       segmented_scan,
                                       sorted_unique_reduce)

N_BIG = 11_075_584     # main program record rows (13 chunks x 851,968)
N_MERGE = 524_288      # merge rows (2 x out_capacity)
CAP = 1 << 18


def t_compile(fn, *shapes, name=""):
    args = [jax.ShapeDtypeStruct(s, d) for s, d in shapes]
    t0 = time.time()
    lowered = jax.jit(fn).lower(*args)
    tl = time.time() - t0
    t0 = time.time()
    lowered.compile()
    tc = time.time() - t0
    print(f"{name:34s} lower {tl:5.1f}s compile {tc:6.1f}s", flush=True)


for N in (N_MERGE, N_BIG):
    tag = f"N={N//1000}k"
    t_compile(lambda x: ladder_cumsum(x), ((N,), np.int32),
              name=f"ladder_cumsum {tag}")
    t_compile(lambda x: ladder_cummax(x), ((N,), np.int32),
              name=f"ladder_cummax {tag}")
    t_compile(lambda k1, k2, v: jax.lax.sort((k1, k2, v), num_keys=2),
              ((N,), np.uint32), ((N,), np.uint32), ((N,), np.int32),
              name=f"variadic sort x3 {tag}")
    t_compile(lambda e: jnp.searchsorted(
        ladder_cumsum(e.astype(np.int32)),
        jnp.arange(1, CAP + 1, dtype=np.int32), side="left"),
        ((N,), bool), name=f"cumsum+searchsorted {tag}")
    t_compile(lambda k, v, p, m: sorted_unique_reduce(
        k, v, p, m, CAP, "sum", unit_values=True),
        ((N, 2), np.uint32), ((N,), np.int32), ((N, 2), np.int32),
        ((N,), bool), name=f"sorted_unique_reduce {tag}")
