"""Long-chain timing: 64 iterations per dispatch; constants ~1%."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp

B, T, H, D = 4, 2048, 16, 64
q = jax.random.normal(jax.random.key(0), (B, H, T, D), jnp.bfloat16)
k = jax.random.normal(jax.random.key(1), (B, H, T, D), jnp.bfloat16)
v = jax.random.normal(jax.random.key(2), (B, H, T, D), jnp.bfloat16)

from jax.experimental.pallas.ops.tpu import flash_attention as fa

fl = 2 * 2 * B * H * T * T * D
N = 64


def timed(step, name, flops):
    @jax.jit
    def run(x):
        def body(c, _):
            return step(c), None
        out, _ = jax.lax.scan(body, x, None, length=N)
        return jnp.sum(out.astype(jnp.float32))

    float(run(q))  # compile + warm
    best = 1e9
    for _ in range(3):
        t0 = time.time()
        float(run(q))
        best = min(best, (time.time() - t0) / N)
    print(f"{name:26s} {best*1e3:7.2f} ms ({flops/best/1e12:5.1f} TF/s)",
          flush=True)


def jnp_attn(x):
    mask = jnp.tril(jnp.ones((T, T), bool))
    s = jnp.einsum("bhqd,bhkd->bhqk", x, k,
                   preferred_element_type=jnp.float32) * (D ** -0.5)
    s = jnp.where(mask[None, None], s, -jnp.inf)
    w = jax.nn.softmax(s, axis=-1).astype(jnp.bfloat16)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v,
                      preferred_element_type=jnp.float32).astype(jnp.bfloat16)


timed(jnp_attn, "jnp fwd", fl)
timed(lambda x: jax.grad(lambda qq: jnp.sum(
    jnp_attn(qq).astype(jnp.float32)))(x).astype(jnp.bfloat16),
    "jnp fwd+bwd(dq)", 3 * fl)


bs = fa.BlockSizes(
    block_q=512, block_k_major=512, block_k=512, block_b=1,
    block_q_major_dkv=512, block_k_major_dkv=512,
    block_k_dkv=512, block_q_dkv=512,
    block_k_major_dq=512, block_k_dq=512, block_q_dq=512,
)


def pl_attn(x):
    return fa.flash_attention(x, k, v, causal=True, sm_scale=D ** -0.5,
                              block_sizes=bs)


timed(pl_attn, "pallas fwd c512", fl)
timed(lambda x: jax.grad(lambda qq: jnp.sum(
    pl_attn(qq).astype(jnp.float32)))(x).astype(jnp.bfloat16),
    "pallas fwd+bwd(dq) c512", 3 * fl)

# grads wrt q, k AND v (the real training need)
def g3(x):
    dq, dk, dv = jax.grad(lambda a, b, c: jnp.sum(fa.flash_attention(
        a, b, c, causal=True, sm_scale=D ** -0.5,
        block_sizes=bs).astype(jnp.float32)), argnums=(0, 1, 2))(x, k, v)
    return (dq + dk + dv).astype(jnp.bfloat16)


timed(g3, "pallas fwd+bwd(dqkv)", 3 * fl)


def g3j(x):
    dq, dk, dv = jax.grad(lambda a, b, c: jnp.sum(
        _attn3(a, b, c).astype(jnp.float32)), argnums=(0, 1, 2))(x, k, v)
    return (dq + dk + dv).astype(jnp.bfloat16)


def _attn3(qx, kx, vx):
    mask = jnp.tril(jnp.ones((T, T), bool))
    s = jnp.einsum("bhqd,bhkd->bhqk", qx, kx,
                   preferred_element_type=jnp.float32) * (D ** -0.5)
    s = jnp.where(mask[None, None], s, -jnp.inf)
    w = jax.nn.softmax(s, axis=-1).astype(jnp.bfloat16)
    return jnp.einsum("bhqk,bhkd->bhqd", w, vx,
                      preferred_element_type=jnp.float32).astype(jnp.bfloat16)


timed(g3j, "jnp fwd+bwd(dqkv)", 3 * fl)
