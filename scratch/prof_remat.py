"""How much context does remat buy on the real chip? Try doubling T
until OOM, with and without remat."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
from mapreduce_tpu.parallel import make_mesh
from mapreduce_tpu.models.transformer import TransformerConfig, TransformerTrainer

mesh = make_mesh()
for remat in (False, True):
    for T in (4096, 8192, 16384, 32768, 65536):
        cfg = TransformerConfig(vocab=32768, embed=1024, n_layers=8,
                                n_heads=16, head_dim=64, ffn=4096,
                                remat=remat)
        try:
            tr = TransformerTrainer(mesh, cfg, learning_rate=1e-3)
            params = tr.init_params()
            toks = np.random.default_rng(0).integers(
                0, cfg.vocab, size=(2, T + 1)).astype(np.int32)
            t0 = time.time()
            params, loss = tr.step(params, toks)
            lv = float(loss)
            t1 = time.time()
            params, loss = tr.step(params, toks)
            lv = float(loss)
            dt = time.time() - t1
            print(f"remat={remat} T={T}: OK {dt:.2f}s/step "
                  f"({2*T/dt:.0f} tok/s)", flush=True)
            del params
        except Exception as e:
            msg = str(e).split("\n")[0][:100]
            print(f"remat={remat} T={T}: FAIL {msg}", flush=True)
            break
