import sys, time
sys.path.insert(0, "/root/repo")
import concurrent.futures as cf
import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P
from mapreduce_tpu.parallel import make_mesh
import bench

mesh = make_mesh()
sh = NamedSharding(mesh, P("data"))
MB = 1 << 20
corpus = bench.make_corpus()
flat = np.frombuffer(corpus, dtype=np.uint8); rows = flat.size // (4 * MB) // 8 * 8; chunks = flat[:rows * 4 * MB].reshape(rows, 4 * MB)

def seq(c):
    outs = [jax.device_put(c[w * (len(c) // 8):(w + 1) * (len(c) // 8)], sh) for w in range(8)]
    jax.block_until_ready(outs)

def thr(c, n):
    with cf.ThreadPoolExecutor(max_workers=n) as ex:
        outs = list(ex.map(
            lambda w: jax.device_put(c[w * (len(c) // 8):(w + 1) * (len(c) // 8)], sh), range(8)))
    jax.block_until_ready(outs)

for rep in range(3):
    c = (chunks.astype(np.int16) + rep * 3).astype(np.uint8)   # fresh content
    t0 = time.time(); seq(c); print(f"rep{rep} seq     {time.time()-t0:6.2f}s", flush=True)
    c = (chunks.astype(np.int16) + rep * 3 + 1).astype(np.uint8)
    t0 = time.time(); thr(c, 8); print(f"rep{rep} thr8    {time.time()-t0:6.2f}s", flush=True)
    c = (chunks.astype(np.int16) + rep * 3 + 2).astype(np.uint8)
    t0 = time.time(); thr(c, 2); print(f"rep{rep} thr2    {time.time()-t0:6.2f}s", flush=True)
