"""Validate 64K/128K single-chip training with the round-5 kernel +
head_dim-128 config (README's remat=True long-context claim)."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np, jax
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
from mapreduce_tpu.models.transformer import TransformerConfig, TransformerTrainer
from mapreduce_tpu.parallel import make_mesh

for T in (65536, 131072):
    cfg = TransformerConfig(vocab=32768, embed=1024, n_layers=8,
                            n_heads=8, head_dim=128, ffn=4096,
                            loss_block=2048, remat=True)
    tr = TransformerTrainer(make_mesh(), cfg, learning_rate=1e-4)
    params = tr.init_params()
    toks = np.random.default_rng(0).integers(
        0, 32768, size=(1, T + 1)).astype(np.int32)
    t0 = time.time()
    params, loss = tr.step(params, toks)
    print(f"T={T}: first step (incl compile) {time.time()-t0:.1f}s "
          f"loss={float(loss):.3f}", flush=True)
    t0 = time.time()
    params, loss = tr.step(params, toks)
    np.asarray(loss)
    sec = time.time() - t0
    print(f"T={T}: steady step {sec:.2f}s = {T/sec/1e3:.1f}k tok/s "
          f"loss={float(loss):.3f}", flush=True)
    del params, tr
