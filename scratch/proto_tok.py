"""Prototype: interpret-mode Pallas tokenizing map-scan kernel."""
import functools
import numpy as np
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import sys
sys.path.insert(0, "/root/repo")
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

L = 128
INT_MIN = -(2 ** 31)
_WS = (32, 9, 10, 13, 12, 11)


def _is_space(b):
    m = b == jnp.uint8(_WS[0])
    for w in _WS[1:]:
        m = m | (b == jnp.uint8(w))
    return m


def _affine_ladder_lanes(m, c):
    lanes = m.shape[-1]
    d = 1
    while d < lanes:
        m_l = jnp.concatenate(
            [jnp.ones(m.shape[:-1] + (d,), m.dtype), m[..., :-d]], axis=-1)
        c_l = jnp.concatenate(
            [jnp.zeros(c.shape[:-1] + (d,), c.dtype), c[..., :-d]], axis=-1)
        m, c = m * m_l, m * c_l + c
        d *= 2
    return m, c


def _max_ladder_lanes(x):
    lanes = x.shape[-1]
    lowest = jnp.iinfo(x.dtype).min
    d = 1
    while d < lanes:
        x = jnp.maximum(x, jnp.concatenate(
            [jnp.full(x.shape[:-1] + (d,), lowest, x.dtype), x[..., :-d]],
            axis=-1))
        d *= 2
    return x


def _tok_kernel(b_ref, nb_ref, *refs, multipliers, R):
    n_lanes = len(multipliers)
    h_refs = refs[:n_lanes]
    end_ref, start_ref, len_ref = refs[n_lanes:n_lanes + 3]
    cps_ref, ch_ref, cs_ref = refs[n_lanes + 3:]
    blk = pl.program_id(0)

    @pl.when(blk == 0)
    def _init():
        cps_ref[0] = jnp.int32(1)   # "previous byte is a separator"
        for i in range(n_lanes):
            ch_ref[i] = jnp.uint32(0)
        cs_ref[0] = jnp.int32(INT_MIN)

    b = b_ref[...]                  # [R, L] uint8
    nb = nb_ref[...]
    space = _is_space(b)
    word = jnp.logical_not(space)
    next_space = _is_space(nb)
    is_end = word & next_space
    # prev_space shifted in flattened order, carry at [0, 0]
    sp32 = space.astype(jnp.int32)
    lastcol = jnp.concatenate(
        [jnp.full((1, 1), cps_ref[0], jnp.int32), sp32[:-1, -1:]], axis=0)
    prev_space = jnp.concatenate([lastcol, sp32[:, :-1]], axis=1) > 0
    is_start = word & prev_space

    b32 = b.astype(jnp.uint32)
    for i, a in enumerate(multipliers):
        m = jnp.where(word, jnp.uint32(a), jnp.uint32(0))
        c = jnp.where(word, b32 + jnp.uint32(1), jnp.uint32(0))
        mw, cw = _affine_ladder_lanes(m, c)
        mr, cr = mw[:, -1], cw[:, -1]           # row totals
        mi, ci = _affine_ladder_lanes(mr[None, :], cr[None, :])
        mi, ci = mi[0], ci[0]
        hc = ch_ref[i]
        comb_c = hc * mi + ci                     # carry ∘ rows 0..r
        cp = jnp.concatenate(
            [jnp.broadcast_to(hc, (1,)).astype(jnp.uint32), comb_c[:-1]])
        h = cp[:, None] * mw + cw
        h_refs[i][...] = h
        ch_ref[i] = h[R - 1, L - 1]

    pos = (jnp.int32(blk) * jnp.int32(R * L)
           + jax.lax.broadcasted_iota(jnp.int32, (R, L), 0) * jnp.int32(L)
           + jax.lax.broadcasted_iota(jnp.int32, (R, L), 1))
    marks = jnp.where(is_start, pos, jnp.int32(-1))
    mw = _max_ladder_lanes(marks)
    rmax = mw[:, -1]
    rinc = _max_ladder_lanes(rmax[None, :])[0]
    cmax = cs_ref[0]
    pmax = jnp.concatenate(
        [jnp.broadcast_to(cmax, (1,)).astype(jnp.int32),
         jnp.maximum(rinc, cmax)[:-1]])
    start = jnp.maximum(mw, pmax[:, None])
    start_ref[...] = start
    len_ref[...] = pos - start + jnp.int32(1)
    end_ref[...] = is_end.astype(jnp.int32)
    cps_ref[0] = sp32[R - 1, L - 1]
    cs_ref[0] = start[R - 1, L - 1]


def tokenize_pallas(chunk, multipliers=(16777619, 0x85EBCA6B), block=1024):
    N = chunk.shape[0]
    R = block // L
    npad = -(-N // block) * block
    pad = npad - N
    cp = jnp.concatenate([chunk, jnp.full((pad,), 32, jnp.uint8)]) \
        if pad else chunk
    nb = jnp.concatenate([cp[1:], jnp.full((1,), 32, jnp.uint8)])
    rows = npad // L
    shape2 = (rows, L)
    spec = pl.BlockSpec((R, L), lambda i: (i, 0))
    n_lanes = len(multipliers)
    outs = pl.pallas_call(
        functools.partial(_tok_kernel, multipliers=tuple(multipliers), R=R),
        grid=(npad // block,),
        in_specs=[spec, spec],
        out_specs=[spec] * (n_lanes + 3),
        out_shape=[jax.ShapeDtypeStruct(shape2, jnp.uint32)] * n_lanes
        + [jax.ShapeDtypeStruct(shape2, jnp.int32)] * 3,
        scratch_shapes=[pltpu.SMEM((1,), jnp.int32),
                        pltpu.SMEM((n_lanes,), jnp.uint32),
                        pltpu.SMEM((1,), jnp.int32)],
        interpret=True,
    )(cp.reshape(shape2), nb.reshape(shape2))
    hs = [o.reshape(-1)[:N] for o in outs[:n_lanes]]
    end, start, length = (o.reshape(-1)[:N] for o in outs[n_lanes:])
    return (end.astype(bool), jnp.stack(hs, axis=-1), start, length)


from mapreduce_tpu.ops.tokenize import tokenize_hash

rng = np.random.default_rng(0)
texts = [
    b"hello world  foo\tbar\nbaz " * 40,
    b"x",
    b" ",
    b"".join(bytes(rng.integers(32, 127, rng.integers(1, 12)).astype(np.uint8))
             + b" " for _ in range(500)),
    b"a" * 3000 + b" b",
]
for t in texts:
    for pad_to in (None, 1024, 1536, 4096):
        n = len(t)
        if pad_to:
            if n > pad_to:
                continue
            t2 = t + b" " * (pad_to - n)
        else:
            t2 = t
        chunk = jnp.asarray(np.frombuffer(t2, dtype=np.uint8))
        exp = tokenize_hash(chunk)
        got_end, got_keys, got_start, got_len = tokenize_pallas(chunk)
        assert np.array_equal(np.asarray(got_end), np.asarray(exp.is_end))
        ie = np.asarray(exp.is_end)
        assert np.array_equal(np.asarray(got_keys)[ie],
                              np.asarray(exp.keys)[ie]), (len(t2),)
        assert np.array_equal(np.asarray(got_start)[ie],
                              np.asarray(exp.start)[ie])
        assert np.array_equal(np.asarray(got_len)[ie],
                              np.asarray(exp.length)[ie])
        # full-array equality too (tile_compact gathers only at ends, but
        # pin everywhere to be strict)
        assert np.array_equal(np.asarray(got_start), np.asarray(exp.start))
    print(f"text len={len(t)} OK")
print("tokenize kernel prototype OK")
