"""Bisect the fwd kernel slowness: strip features one at a time."""
import sys, time, functools
sys.path.insert(0, "/root/repo")
import numpy as np, jax, jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

B, T, H, D = 4, 2048, 16, 64
BQ = BKV = 512
q = jax.random.normal(jax.random.key(0), (B, H, T, D), jnp.bfloat16)
k = jax.random.normal(jax.random.key(1), (B, H, T, D), jnp.bfloat16)
v = jax.random.normal(jax.random.key(2), (B, H, T, D), jnp.bfloat16)
fl = 2 * 2 * B * H * T * T * D


def timed(f, name):
    t0 = time.time()
    out = f(q)
    np.asarray(out).ravel()[:1]
    comp = time.time() - t0
    t0 = time.time()
    for _ in range(10):
        out = f(out)
    np.asarray(out).ravel()[:1]
    ms = (time.time() - t0) / 10 * 1e3
    print(f"{name:34s} {ms:8.2f} ms ({fl/ms*1e3/1e12:5.1f} TF/s) "
          f"[compile {comp:.0f}s]", flush=True)


def qmap(b, h, i, j):
    return (b, h, i, 0)


def kvmap(b, h, i, j):
    return (b, h, j, 0)


def build(body, n_scr, causal_skip=False):
    specs = dict(
        grid=(B, H, T // BQ, T // BKV),
        in_specs=[pl.BlockSpec((1, 1, BQ, D), qmap),
                  pl.BlockSpec((1, 1, BKV, D), kvmap),
                  pl.BlockSpec((1, 1, BKV, D), kvmap)],
        out_specs=pl.BlockSpec((1, 1, BQ, D), qmap),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((BQ, 128), jnp.float32)
                        for _ in range(n_scr - 1)]
        + [pltpu.VMEM((BQ, D), jnp.float32)],
    )
    call = pl.pallas_call(body, **specs)
    return jax.jit(lambda a: call(a, k, v))


# V1: pure matmul-chain, no softmax, no state
def v1(q_ref, k_ref, v_ref, o_ref, acc):
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _():
        acc[...] = jnp.zeros_like(acc)

    s = jax.lax.dot_general(q_ref[0, 0], k_ref[0, 0],
                            (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    acc[...] += jax.lax.dot_general(s.astype(jnp.bfloat16), v_ref[0, 0],
                                    (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
    o_ref[0, 0] = acc[...].astype(o_ref.dtype)


timed(build(v1, 1), "v1 matmuls+acc only")


# V2: + online softmax state in full-width scratch (no partial stores)
def v2(q_ref, k_ref, v_ref, o_ref, m_scr, d_scr, acc):
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _():
        m_scr[...] = jnp.full_like(m_scr, -1e30)
        d_scr[...] = jnp.zeros_like(d_scr)
        acc[...] = jnp.zeros_like(acc)

    s = jax.lax.dot_general(q_ref[0, 0], k_ref[0, 0],
                            (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * 0.125
    m_prev = m_scr[:, 0:1]
    m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    corr = jnp.exp(m_prev - m_new)
    den = d_scr[:, 0:1] * corr + p.sum(axis=-1, keepdims=True)
    acc[...] = acc[...] * corr + jax.lax.dot_general(
        p.astype(jnp.bfloat16), v_ref[0, 0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_scr[:, 0:1] = m_new
    d_scr[:, 0:1] = den
    o_ref[0, 0] = (acc[...] / jnp.maximum(den, 1e-30)).astype(o_ref.dtype)


timed(build(v2, 3), "v2 +online softmax")


# V3: + causal mask iota/where (no skip)
def v3(q_ref, k_ref, v_ref, o_ref, m_scr, d_scr, acc):
    i = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _():
        m_scr[...] = jnp.full_like(m_scr, -1e30)
        d_scr[...] = jnp.zeros_like(d_scr)
        acc[...] = jnp.zeros_like(acc)

    s = jax.lax.dot_general(q_ref[0, 0], k_ref[0, 0],
                            (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * 0.125
    qp = i * BQ + jax.lax.broadcasted_iota(jnp.int32, (BQ, BKV), 0)
    kp = j * BKV + jax.lax.broadcasted_iota(jnp.int32, (BQ, BKV), 1)
    s = jnp.where(kp <= qp, s, -1e30)
    m_prev = m_scr[:, 0:1]
    m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    corr = jnp.exp(m_prev - m_new)
    den = d_scr[:, 0:1] * corr + p.sum(axis=-1, keepdims=True)
    acc[...] = acc[...] * corr + jax.lax.dot_general(
        p.astype(jnp.bfloat16), v_ref[0, 0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_scr[:, 0:1] = m_new
    d_scr[:, 0:1] = den
    o_ref[0, 0] = (acc[...] / jnp.maximum(den, 1e-30)).astype(o_ref.dtype)


timed(build(v3, 3), "v3 +causal mask")


# V4: v3 + pl.when causal tile skip
def v4(q_ref, k_ref, v_ref, o_ref, m_scr, d_scr, acc):
    i = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _():
        m_scr[...] = jnp.full_like(m_scr, -1e30)
        d_scr[...] = jnp.zeros_like(d_scr)
        acc[...] = jnp.zeros_like(acc)

    @pl.when(j * BKV <= i * BQ + BQ - 1)
    def _():
        s = jax.lax.dot_general(q_ref[0, 0], k_ref[0, 0],
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * 0.125
        qp = i * BQ + jax.lax.broadcasted_iota(jnp.int32, (BQ, BKV), 0)
        kp = j * BKV + jax.lax.broadcasted_iota(jnp.int32, (BQ, BKV), 1)
        s = jnp.where(kp <= qp, s, -1e30)
        m_prev = m_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        den = d_scr[:, 0:1] * corr + p.sum(axis=-1, keepdims=True)
        acc[...] = acc[...] * corr + jax.lax.dot_general(
            p.astype(jnp.bfloat16), v_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:, 0:1] = m_new
        d_scr[:, 0:1] = den

    o_ref[0, 0] = (acc[...] / jnp.maximum(d_scr[:, 0:1], 1e-30)
                   ).astype(o_ref.dtype)


timed(build(v4, 3), "v4 +tile skip")
