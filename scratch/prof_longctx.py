"""Long-context ceiling on one real chip with remat + chunked attention."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
from mapreduce_tpu.parallel import make_mesh
from mapreduce_tpu.models.transformer import TransformerConfig, TransformerTrainer

mesh = make_mesh()
for T in (32768,):
    cfg = TransformerConfig(vocab=32768, embed=1024, n_layers=8,
                            n_heads=16, head_dim=64, ffn=4096,
                            remat=True, attn_block=1024, loss_block=2048)
    try:
        tr = TransformerTrainer(mesh, cfg, learning_rate=1e-3)
        params = tr.init_params()
        toks = np.random.default_rng(0).integers(
            0, cfg.vocab, size=(1, T + 1)).astype(np.int32)
        params, loss = tr.step(params, toks); lv = float(loss)
        t1 = time.time()
        params, loss = tr.step(params, toks); lv = float(loss)
        dt = time.time() - t1
        print(f"T={T}: OK {dt:.2f}s/step ({T/dt:.0f} tok/s) loss={lv:.2f}",
              flush=True)
        del params, tr
    except Exception as e:
        print(f"T={T}: FAIL {str(e).split(chr(10))[0][:90]}", flush=True)
        break
