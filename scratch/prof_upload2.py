"""Round 2: combine the winners (int32 view x threaded slabs), repeat
trials, and measure overlap potential (upload while a compute runs)."""

import concurrent.futures as cf
import time

import jax
import jax.numpy as jnp
import numpy as np

MB = 1 << 20
SIZE = 256 * MB

dev = jax.devices()[0]
data = np.random.default_rng(0).integers(0, 255, size=SIZE,
                                         dtype=np.uint8)
data32 = data.view(np.int32)


def timed(label, fn, reps=3):
    best = float("inf")
    for _ in range(reps):
        t0 = time.time()
        out = fn()
        jax.block_until_ready(out)
        best = min(best, time.time() - t0)
        del out
    print(f"{label:46s} {best:7.2f}s  {SIZE / MB / best:7.1f} MB/s")
    return best


timed("single put uint8", lambda: jax.device_put(data, dev))
timed("single put int32 view", lambda: jax.device_put(data32, dev))

pool = cf.ThreadPoolExecutor(max_workers=32)

for arr, tag in ((data, "uint8"), (data32, "int32")):
    for n in (4, 8, 16, 32):
        per = arr.size // n

        def threaded(arr=arr, n=n, per=per):
            return list(pool.map(
                lambda i: jax.device_put(arr[i * per:(i + 1) * per], dev),
                range(n)))

        timed(f"{n} slabs threaded no-concat {tag}", threaded)
pool.shutdown()
