"""Variant A: dispatch each wave only after its input is resident.
Variant B: all dispatches up front (current run()).
Variant C: pure transfers, no compute (control)."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
import jax.numpy as jnp
import bench
from mapreduce_tpu.engine import DeviceWordCount, EngineConfig
from mapreduce_tpu.ops.tokenize import shard_text
from mapreduce_tpu.parallel import make_mesh

corpus = bench.make_corpus()
mesh = make_mesh()
wc = DeviceWordCount(mesh, chunk_len=1 << 22,
                     config=EngineConfig(local_capacity=1 << 18,
                                         exchange_capacity=1 << 17,
                                         out_capacity=1 << 18))
n_chunks = -(-len(corpus) // wc.chunk_len)
chunks, L = shard_text(corpus, n_chunks, pad_multiple=wc.config.tile)
eng = wc._engine_for(L)
fn = eng._get_compiled(eng.config)

wi, n_real = eng._shard_inputs(chunks, 8)
outs = [fn(*(w if isinstance(w, tuple) else w.result()), n_real) for w in wi]
jax.block_until_ready([o[4] for o in outs])
del wi, outs
print("warm", flush=True)

def variant_A():
    wave_inputs, nr = eng._shard_inputs(chunks, 8)
    outs = []
    for w in range(8):
        ci, ii = wave_inputs[w] if isinstance(wave_inputs[w], tuple) \
            else wave_inputs[w].result()
        jax.block_until_ready(ci)          # input resident FIRST
        outs.append(fn(ci, ii, nr))        # then dispatch
    jax.block_until_ready([o[4] for o in outs])

def variant_B():
    wave_inputs, nr = eng._shard_inputs(chunks, 8)
    outs = [fn(*(w if isinstance(w, tuple) else w.result()), nr)
            for w in wave_inputs]
    jax.block_until_ready([o[4] for o in outs])

def variant_C():
    wave_inputs, nr = eng._shard_inputs(chunks, 8)
    arrs = [w if isinstance(w, tuple) else w.result()
            for w in wave_inputs]
    jax.block_until_ready([a[0] for a in arrs])

for trial in range(2):
    for name, v in (("C transfers only", variant_C),
                    ("A dispatch-after-ready", variant_A),
                    ("B dispatch-up-front", variant_B)):
        t0 = time.time(); v()
        print(f"trial{trial} {name:24s} {time.time()-t0:6.2f}s", flush=True)
