"""lax.sort compile-time scaling: num_keys x operand count (CPU)."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp

N = 524_288


def t_compile(fn, shapes, name):
    args = [jax.ShapeDtypeStruct(s, d) for s, d in shapes]
    t0 = time.time()
    c = jax.jit(fn).lower(*args).compile()
    print(f"{name:44s} compile {time.time()-t0:6.1f}s", flush=True)


u32 = np.uint32
i32 = np.int32

t_compile(lambda a: jax.lax.sort((a,), num_keys=1)[0],
          [((N,), u32)], "1 key, 1 operand")
t_compile(lambda a, b: jax.lax.sort((a, b), num_keys=1),
          [((N,), u32), ((N,), i32)], "1 key, 2 operands")
t_compile(lambda a, b, c: jax.lax.sort((a, b, c), num_keys=1),
          [((N,), u32), ((N,), i32), ((N,), i32)], "1 key, 3 operands")
t_compile(lambda a, b: jax.lax.sort((a, b), num_keys=2),
          [((N,), u32), ((N,), u32)], "2 keys, 2 operands")
t_compile(lambda a, b, c: jax.lax.sort((a, b, c), num_keys=2),
          [((N,), u32), ((N,), u32), ((N,), i32)], "2 keys, 3 operands")
t_compile(lambda a, b, c, d, e: jax.lax.sort((a, b, c, d, e), num_keys=2),
          [((N,), u32), ((N,), u32), ((N,), i32), ((N,), i32),
           ((N,), i32)], "2 keys, 5 operands")
# two-pass stable single-key lexicographic equivalent
t_compile(lambda a, b, c: jax.lax.sort(
    jax.lax.sort((b, a, c), num_keys=1), num_keys=1),
    [((N,), u32), ((N,), u32), ((N,), i32)],
    "two-pass stable 1-key (lexicographic)")
# argsort + gather
t_compile(lambda a, b, c: tuple(
    x[jnp.argsort(a, stable=True)] for x in (a, b, c)),
    [((N,), u32), ((N,), u32), ((N,), i32)], "argsort + 3 gathers")
