"""Which programs poison transfers? trivial / small-wave / big engine."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np, jax, jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
import bench
from mapreduce_tpu.engine import DeviceWordCount, EngineConfig
from mapreduce_tpu.ops.tokenize import shard_text
from mapreduce_tpu.parallel import make_mesh

mesh = make_mesh()
sh = NamedSharding(mesh, P("data"))
corpus = bench.make_corpus()
chunks, L = shard_text(corpus, 94, pad_multiple=512)

def put(tag):
    t0 = time.time()
    out = jax.device_put(chunks, sh)
    jax.block_until_ready(out)
    dt = time.time() - t0
    print(f"{tag:40s} {dt:6.2f}s {chunks.nbytes/1e6/dt:7.0f} MB/s", flush=True)
    del out

put("baseline put")

# trivial program
f = jax.jit(lambda x: (x.astype(jnp.int32) * 2).sum())
r = f(jnp.ones((1024, 1024), jnp.uint8)); np.asarray(r)
put("after trivial program")

# medium: 1GB-workingset matmul
g = jax.jit(lambda a, b: a @ b)
a = jnp.ones((8192, 8192), jnp.bfloat16)
r = g(a, a); jax.block_until_ready(r); del r, a
put("after 8k matmul (~400MB ws)")

# one WAVE of the engine (12 chunks, ~200MB records buffer)
wc = DeviceWordCount(mesh, chunk_len=1 << 22,
                     config=EngineConfig(local_capacity=1 << 18,
                                         exchange_capacity=1 << 17,
                                         out_capacity=1 << 18))
eng = wc._engine_for(L)
fn = eng._get_compiled(eng.config)
dev = jax.device_put(chunks[:12], sh)
out = fn(dev, jax.device_put(np.arange(12, dtype=np.int32), sh), np.int32(12))
v = np.asarray(out[4]); del out, dev
put("after ONE 12-chunk wave")
put("again")
