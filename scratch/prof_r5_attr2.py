"""Round-5 32K attribution, device-side scan edition.

prof_r5_attr.py's per-call slope timing produced negative times and >100%
peak over the tunnel (async dispatch artifacts — round-4 note: per-call
timing is useless here).  This version puts the repetition INSIDE the
program with lax.scan, so one dispatch + one readback times N dependent
iterations; slope between N and N//3 cancels dispatch + readback.
"""
import sys, time, os
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

PEAK = 197e12
B, T, E, F, V = 1, 32768, 1024, 4096, 32768

from mapreduce_tpu.ops.flash_attention import flash_attention


def timed_scan(make_step, x0, n_hi=24, n_lo=8, what="", flops_per_iter=0.0,
               useful_frac=1.0):
    """Time a dependent chain of make_step applied n times inside scan."""
    def run(n):
        @jax.jit
        def prog(x):
            def body(c, _):
                return make_step(c), None
            y, _ = jax.lax.scan(body, x, None, length=n)
            return y
        r = prog(x0)          # compile + warm
        np.asarray(jax.tree.leaves(r)[0]).ravel()[:1]
        best = np.inf
        for _ in range(3):
            t0 = time.time()
            r = prog(x0)
            np.asarray(jax.tree.leaves(r)[0]).ravel()[:1]
            best = min(best, time.time() - t0)
        return best
    t_hi, t_lo = run(n_hi), run(n_lo)
    sec = (t_hi - t_lo) / (n_hi - n_lo)
    fl = flops_per_iter
    useful = fl * useful_frac
    print(f"{what:28s}: {sec*1e3:8.2f} ms/iter  dense {fl/sec/1e12:6.1f}"
          f" TF/s  useful {useful/sec/1e12:6.1f} TF/s "
          f"({useful/sec/PEAK*100:5.1f}% peak)", flush=True)
    return sec


def attn_fwd(H, D):
    k = jax.random.normal(jax.random.key(1), (B, H, T, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.key(2), (B, H, T, D), jnp.bfloat16)
    q = jax.random.normal(jax.random.key(0), (B, H, T, D), jnp.bfloat16)
    fl = 2 * 2 * B * H * T * T * D
    timed_scan(lambda x: flash_attention(x, k, v, causal=True), q,
               what=f"attn fwd H={H} D={D}", flops_per_iter=fl,
               useful_frac=0.5)


def attn_train(H, D):
    k = jax.random.normal(jax.random.key(1), (B, H, T, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.key(2), (B, H, T, D), jnp.bfloat16)
    q = jax.random.normal(jax.random.key(0), (B, H, T, D), jnp.bfloat16)
    fl = 6 * 2 * B * H * T * T * D

    def loss(x):
        return jnp.sum(flash_attention(x, k, v, causal=True
                                       ).astype(jnp.float32))

    def step(x):
        return (x - 1e-3 * jax.grad(loss)(x)).astype(jnp.bfloat16)

    timed_scan(step, q, n_hi=12, n_lo=4,
               what=f"attn fwd+bwd H={H} D={D}", flops_per_iter=fl,
               useful_frac=0.5)


attn_fwd(16, 64)
attn_fwd(8, 128)
attn_train(16, 64)
attn_train(8, 128)

# dense parts
xin = jax.random.normal(jax.random.key(3), (B, T, E), jnp.bfloat16)
w_in = jax.random.normal(jax.random.key(5), (E, F), jnp.bfloat16)
w_out = jax.random.normal(jax.random.key(6), (F, E), jnp.bfloat16)


def ffn_loss(x):
    u = jax.nn.gelu(jnp.einsum("bte,ef->btf", x, w_in))
    y = x + jnp.einsum("btf,fe->bte", u, w_out)
    return jnp.sum(y.astype(jnp.float32))


def ffn_step(x):
    return (x - 1e-3 * jax.grad(ffn_loss)(x)).astype(jnp.bfloat16)


timed_scan(ffn_step, xin, n_hi=24, n_lo=8, what="ffn fwd+bwd",
           flops_per_iter=6 * B * T * 2 * E * F)

wq = jax.random.normal(jax.random.key(7), (E, E), jnp.bfloat16) * 0.01


def proj_loss(x):
    return jnp.sum((x + jnp.einsum("bte,ef->btf", x, wq)
                    ).astype(jnp.float32))


def proj_step(x):
    return (x - 1e-3 * jax.grad(proj_loss)(x)).astype(jnp.bfloat16)


timed_scan(proj_step, xin, n_hi=32, n_lo=8, what="proj fwd+bwd",
           flops_per_iter=6 * B * T * E * E)

unemb = jax.random.normal(jax.random.key(4), (E, V), jnp.bfloat16)
tgt = jnp.asarray(np.random.default_rng(0).integers(0, V, (B, T)),
                  jnp.int32)


def head_loss(x, Tc=2048):
    C = T // Tc
    xs = jnp.moveaxis(x.reshape(B, C, Tc, E), 1, 0)
    ts = jnp.moveaxis(tgt.reshape(B, C, Tc), 1, 0)

    def chunk(_, xt):
        x_c, t_c = xt
        logits = jnp.einsum("bte,ev->btv", x_c, unemb,
                            preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        tl = jnp.take_along_axis(logits, t_c[..., None], axis=-1)[..., 0]
        return None, (lse - tl)

    _, nll = jax.lax.scan(jax.checkpoint(chunk), None, (xs, ts))
    return jnp.mean(nll)


def head_step(x):
    return (x - 1e-3 * jax.grad(head_loss)(x)).astype(jnp.bfloat16)


timed_scan(head_step, xin, n_hi=12, n_lo=4, what="loss head (scan)",
           flops_per_iter=6 * B * T * E * V)
