"""Profile host->device upload throughput on the real chip.

Questions: (1) what MB/s does the tunnelled link sustain for one big
device_put, (2) does splitting into N async slabs help, (3) do Python
threads issuing device_put concurrently help, (4) does the on-device
concatenate cost matter.  Drives the DeviceEngine.UPLOAD_SLABS choice and
the wave-pipeline design (upload of wave i+1 overlapped with compute of
wave i).
"""

import concurrent.futures as cf
import time

import jax
import jax.numpy as jnp
import numpy as np

MB = 1 << 20
SIZE = 256 * MB

dev = jax.devices()[0]
print("platform:", dev.platform)
data = np.random.default_rng(0).integers(0, 255, size=SIZE,
                                         dtype=np.uint8)


def timed(label, fn):
    t0 = time.time()
    out = fn()
    jax.block_until_ready(out)
    dt = time.time() - t0
    print(f"{label:42s} {dt:7.2f}s  {SIZE / MB / dt:7.1f} MB/s")
    return dt


# 1) one giant transfer
timed("single device_put", lambda: jax.device_put(data, dev))

# 2) N slabs, async dispatch then concat
for n in (4, 8, 16, 32, 64):
    per = SIZE // n

    def slabs(n=n, per=per):
        parts = [jax.device_put(data[i * per:(i + 1) * per], dev)
                 for i in range(n)]
        return jnp.concatenate(parts)

    timed(f"{n} slabs async + concat", slabs)

# 3) N slabs via thread pool
for n in (8, 16, 32):
    per = SIZE // n

    def threaded(n=n, per=per):
        with cf.ThreadPoolExecutor(max_workers=n) as ex:
            parts = list(ex.map(
                lambda i: jax.device_put(data[i * per:(i + 1) * per], dev),
                range(n)))
        return jnp.concatenate(parts)

    timed(f"{n} slabs threaded + concat", threaded)

# 4) slabs WITHOUT the concat (what pure transfer costs)
for n in (16,):
    per = SIZE // n

    def noconcat(n=n, per=per):
        return [jax.device_put(data[i * per:(i + 1) * per], dev)
                for i in range(n)]

    timed(f"{n} slabs async, no concat", noconcat)

# 5) does dtype matter? (uint8 vs int32 view, same bytes)
data32 = data.view(np.int32)
timed("single device_put int32 view", lambda: jax.device_put(data32, dev))
