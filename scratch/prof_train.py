import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
from mapreduce_tpu.parallel import make_mesh
from mapreduce_tpu.models.transformer import TransformerConfig, TransformerTrainer

mesh = make_mesh()
cfg = TransformerConfig(vocab=32768, embed=1024, n_layers=8,
                        n_heads=16, head_dim=64, ffn=4096)
tr = TransformerTrainer(mesh, cfg, learning_rate=1e-3)
params = tr.init_params()
rng = np.random.default_rng(0)
toks = rng.integers(0, cfg.vocab, size=(4, 2049)).astype(np.int32)
x, y = tr.place_batch(toks)

params, loss = tr._train_step(params, x, y)
jax.block_until_ready(loss)
print("loss after 1 step:", float(loss), flush=True)

for i in range(3):
    t0 = time.time()
    params, loss = tr._train_step(params, x, y)
    jax.block_until_ready(loss)
    lv = float(loss)
    print(f"step {i}: {time.time()-t0:.4f}s loss={lv:.4f}", flush=True)

# also block on a param leaf, not just loss
t0 = time.time()
params, loss = tr._train_step(params, x, y)
jax.block_until_ready(params["embed"])
print(f"blocked on params: {time.time()-t0:.4f}s", flush=True)
