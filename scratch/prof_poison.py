"""Does executing the engine program degrade subsequent transfer speed?"""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P
import bench
from mapreduce_tpu.engine import DeviceWordCount, EngineConfig
from mapreduce_tpu.ops.tokenize import shard_text
from mapreduce_tpu.parallel import make_mesh

mesh = make_mesh()
sh = NamedSharding(mesh, P("data"))
corpus = bench.make_corpus()
chunks, L = shard_text(corpus, 94, pad_multiple=512)

def t_put(label):
    t0 = time.time()
    out = jax.device_put(chunks, sh)
    jax.block_until_ready(out)
    print(f"{label:44s} {time.time()-t0:6.2f}s", flush=True)
    return out

t_put("put before any engine run")
dev = t_put("put again")

wc = DeviceWordCount(mesh, chunk_len=1 << 22,
                     config=EngineConfig(local_capacity=1 << 18,
                                         exchange_capacity=1 << 17,
                                         out_capacity=1 << 18))
eng = wc._engine_for(L)
fn = eng._get_compiled(eng.config)
t0 = time.time()
out = fn(dev, jax.device_put(np.arange(94, dtype=np.int32), sh), np.int32(94))
jax.block_until_ready(out[4])
print(f"engine program ran in {time.time()-t0:6.2f}s (incl compile)", flush=True)

t_put("put right after engine run")
del out
t_put("put after deleting outputs")
time.sleep(5)
t_put("put after 5s sleep")
t0 = time.time()
out2 = fn(dev, jax.device_put(np.arange(94, dtype=np.int32), sh), np.int32(94))
jax.block_until_ready(out2[4])
print(f"engine program (warm) ran in {time.time()-t0:6.2f}s", flush=True)
t_put("put right after warm engine run")
