"""Sweep engine capacities at full scale: compute time vs overflow."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np, jax
import bench
from mapreduce_tpu.engine import DeviceWordCount, EngineConfig
from mapreduce_tpu.parallel import make_mesh

corpus = bench.make_corpus()
mesh = make_mesh()

for tr_ in (112, 104):
    wc = DeviceWordCount(mesh, chunk_len=1 << 22,
                         config=EngineConfig(local_capacity=1 << 18,
                                             exchange_capacity=1 << 17,
                                             out_capacity=1 << 18,
                                             tile=512, tile_records=tr_))
    handle = wc.stage(corpus)
    tm = {}
    t0 = time.time()
    counts = wc.count_staged(handle, timings=tm)
    ok = sum(counts.values()) == 49158635
    print(f"tile_records={tr_}: wall {time.time()-t0:6.2f}s ok={ok} "
          f"compute={tm.get('compute_s')}s waves={tm.get('waves')}",
          flush=True)
    del handle, wc
