"""Does tearing down and rebuilding the backend restore fast transfers?"""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np, jax, jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
import bench
from mapreduce_tpu.ops.tokenize import shard_text

import jax.extend.backend

corpus = bench.make_corpus()
chunks, L = shard_text(corpus, 94, pad_multiple=512)

def put(tag):
    from mapreduce_tpu.parallel import make_mesh
    mesh = jax.sharding.Mesh(
        np.array(jax.devices()[:1]).reshape(1, 1), ("model", "data"))
    sh = NamedSharding(mesh, P("data"))
    t0 = time.time()
    out = jax.device_put(chunks, sh)
    jax.block_until_ready(out)
    print(f"{tag:36s} {time.time()-t0:6.2f}s", flush=True)
    return out

x = put("fresh put")
f = jax.jit(lambda x: x.astype(jnp.int32).sum())
print("consume:", int(np.asarray(f(x))), flush=True)
del x
y = put("post-execution put")
del y, f

t0 = time.time()
jax.extend.backend.clear_backends()
print(f"clear_backends {time.time()-t0:.2f}s", flush=True)
z = put("put after clear_backends")
g = jax.jit(lambda x: x.astype(jnp.int32).sum())
t0 = time.time()
print("consume:", int(np.asarray(g(z))),
      f"({time.time()-t0:.2f}s incl recompile)", flush=True)
del z
put("post-execution put 2")
