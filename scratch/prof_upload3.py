"""Why does upload degrade across bench runs? Test: fresh vs reused host
arrays, holding vs freeing device buffers, 393MB scale."""
import time
import jax
import jax.numpy as jnp
import numpy as np

MB = 1 << 20
SIZE = 393 * MB
dev = jax.devices()[0]

def put(arr, label):
    t0 = time.time()
    out = jax.device_put(arr, dev)
    jax.block_until_ready(out)
    dt = time.time() - t0
    print(f"{label:44s} {dt:6.2f}s {arr.nbytes / MB / dt:7.1f} MB/s",
          flush=True)
    return out

base = np.random.default_rng(0).integers(0, 255, size=SIZE, dtype=np.uint8)

# A: same array, repeated, dropping device buffer each time
for i in range(3):
    out = put(base, f"A{i} reused host arr, drop dev buf")
    del out

# B: fresh host copy each time (like _shard_inputs building padded/ordered)
for i in range(3):
    fresh = base.copy()
    out = put(fresh, f"B{i} fresh host copy, drop dev buf")
    del out, fresh

# C: fresh 2D + fancy-index permutation (exactly what _shard_inputs does)
for i in range(3):
    chunks = base.reshape(94 - 1 + 1, -1)[: 93 * 1]  # ~389MB 2D
    k = chunks.shape[0]
    order = np.arange(k).reshape(k, 1).T.reshape(-1)
    ordered = chunks[np.random.permutation(k)]
    out = put(ordered, f"C{i} fresh permuted 2D")
    del out, ordered
