"""In-tree flash kernel vs shipped vs jnp at bench shapes (TPU)."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp

from mapreduce_tpu.ops.flash_attention import flash_attention

B, T, H, D = 4, 2048, 16, 64
q = jax.random.normal(jax.random.key(0), (B, H, T, D), jnp.bfloat16)
k = jax.random.normal(jax.random.key(1), (B, H, T, D), jnp.bfloat16)
v = jax.random.normal(jax.random.key(2), (B, H, T, D), jnp.bfloat16)

fl = 2 * 2 * B * H * T * T * D
N = 64


def timed(step, name, flops):
    @jax.jit
    def run(x):
        def body(c, _):
            return step(c), None
        out, _ = jax.lax.scan(body, x, None, length=N)
        return jnp.sum(out.astype(jnp.float32))

    t0 = time.time()
    float(run(q))  # compile + warm
    compile_s = time.time() - t0
    best = 1e9
    for _ in range(3):
        t0 = time.time()
        float(run(q))
        best = min(best, (time.time() - t0) / N)
    print(f"{name:30s} {best*1e3:7.2f} ms ({flops/best/1e12:5.1f} TF/s) "
          f"[compile {compile_s:.0f}s]", flush=True)


for bq, bkv in [(512, 512), (256, 512), (512, 1024), (1024, 512),
                (2048, 512), (512, 2048)]:
    def f(x, bq=bq, bkv=bkv):
        return flash_attention(x, k, v, causal=True,
                               block_q=bq, block_kv=bkv)
    timed(f, f"flash fwd q{bq}/kv{bkv}", fl)

    def g(x, bq=bq, bkv=bkv):
        return jax.grad(lambda a: jnp.sum(flash_attention(
            a, k, v, causal=True, block_q=bq,
            block_kv=bkv).astype(jnp.float32)))(x).astype(jnp.bfloat16)
    timed(g, f"flash f+b(dq-only) q{bq}/kv{bkv}", 3 * fl)

    def g3(x, bq=bq, bkv=bkv):
        dq, dk, dv = jax.grad(lambda a, b, c: jnp.sum(flash_attention(
            a, b, c, causal=True, block_q=bq,
            block_kv=bkv).astype(jnp.float32)),
            argnums=(0, 1, 2))(x, k, v)
        return (dq + dk + dv).astype(jnp.bfloat16)
    timed(g3, f"flash f+b(dqkv) q{bq}/kv{bkv}", 3 * fl)
