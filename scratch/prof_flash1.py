"""Shipped flash attention: fwd-only vs bwd, block-size sweep."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp

B, T, H, D = 4, 2048, 16, 64
q = jax.random.normal(jax.random.key(0), (B, H, T, D), jnp.bfloat16)
k = jax.random.normal(jax.random.key(1), (B, H, T, D), jnp.bfloat16)
v = jax.random.normal(jax.random.key(2), (B, H, T, D), jnp.bfloat16)

from jax.experimental.pallas.ops.tpu import flash_attention as fa


def slope(f, n=20):
    out = None
    for _ in range(3):
        out = f()
    np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
    def run(n):
        o = None
        for _ in range(n):
            o = f()
        np.asarray(jax.tree.leaves(o)[0]).ravel()[:1]
    t0 = time.time(); run(n // 4); ts = time.time() - t0
    t0 = time.time(); run(n); tb = time.time() - t0
    return (tb - ts) / (n - n // 4)


fl = 2 * 2 * B * H * T * T * D

fwd = jax.jit(lambda: fa.flash_attention(q, k, v, causal=True,
                                         sm_scale=D ** -0.5))
s = slope(fwd)
print(f"fwd default blocks : {s*1e3:7.2f} ms ({fl/s/1e12:5.1f} TF/s)",
      flush=True)

for bq, bkv in [(512, 512), (256, 512), (512, 1024), (1024, 1024),
                (256, 256)]:
    bs = fa.BlockSizes(
        block_q=bq, block_k_major=bkv, block_k=bkv, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bkv,
        block_k_dkv=bkv, block_q_dkv=bq,
        block_k_major_dq=bkv, block_k_dq=bkv, block_q_dq=bq,
    )
    f = jax.jit(lambda bs=bs: fa.flash_attention(
        q, k, v, causal=True, sm_scale=D ** -0.5, block_sizes=bs))
    s = slope(f)
    print(f"fwd q{bq:4d}/kv{bkv:4d}  : {s*1e3:7.2f} ms "
          f"({fl/s/1e12:5.1f} TF/s)", flush=True)

    def lossf(q, k, v, bs=bs):
        o = fa.flash_attention(q, k, v, causal=True, sm_scale=D ** -0.5,
                               block_sizes=bs)
        return jnp.sum(o.astype(jnp.float32))
    g = jax.jit(jax.grad(lossf, argnums=(0, 1, 2)))
    s = slope(lambda: g(q, k, v))
    print(f"f+b q{bq:4d}/kv{bkv:4d}  : {s*1e3:7.2f} ms "
          f"({3*fl/s/1e12:5.1f} TF/s)", flush=True)
