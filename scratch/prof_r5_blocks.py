"""Round-5 block sweep with the reworked kernel (diag-split, pre-scaled
q, emit-once): does 1024 stay the sweet spot at 2K and 32K, and where do
the clean (uncontended) dense parts land?"""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

PEAK = 197e12
from mapreduce_tpu.ops.flash_attention import flash_attention


def timed(make_step, x0, n, what, fl, useful_frac=1.0):
    @jax.jit
    def prog(x):
        def body(c, _):
            return make_step(c), None
        y, _ = jax.lax.scan(body, x, None, length=n)
        return y

    r = prog(x0)
    np.asarray(jax.tree.leaves(r)[0]).ravel()[:1]
    best = np.inf
    for _ in range(4):
        t0 = time.time()
        r = prog(x0)
        np.asarray(jax.tree.leaves(r)[0]).ravel()[:1]
        best = min(best, time.time() - t0)
    sec = best / n
    useful = fl * useful_frac
    print(f"{what:34s}: {sec*1e3:8.2f} ms/iter  useful "
          f"{useful/sec/1e12:6.1f} TF/s ({useful/sec/PEAK*100:5.1f}%)",
          flush=True)
    return sec


def attn_case(B, T, bq, bkv, n):
    H, D = 8, 128
    k = jax.random.normal(jax.random.key(1), (B, H, T, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.key(2), (B, H, T, D), jnp.bfloat16)
    q = jax.random.normal(jax.random.key(0), (B, H, T, D), jnp.bfloat16)

    def loss(x):
        return jnp.sum(flash_attention(x, k, v, causal=True, block_q=bq,
                                       block_kv=bkv).astype(jnp.float32))

    def step(x):
        return (x - 1e-3 * jax.grad(loss)(x)).astype(jnp.bfloat16)

    fl = 6 * 2 * B * H * T * T * D
    timed(step, q, n, f"attn f+b B{B} T{T} bq{bq} bkv{bkv}", fl, 0.5)


# 32K flagship shape
for bq, bkv in ((1024, 1024), (512, 1024), (256, 1024), (1024, 512),
                (512, 2048)):
    try:
        attn_case(1, 32768, bq, bkv, 48)
    except Exception as e:
        print(f"bq{bq} bkv{bkv}: {type(e).__name__} (vmem?)", flush=True)
# 2K bench shape (B=4)
for bq, bkv in ((1024, 1024), (512, 1024), (512, 512), (256, 1024)):
    try:
        attn_case(4, 2048, bq, bkv, 96)
    except Exception as e:
        print(f"bq{bq} bkv{bkv}: {type(e).__name__}", flush=True)
