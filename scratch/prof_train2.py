import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
from mapreduce_tpu.parallel import make_mesh
from mapreduce_tpu.models.transformer import TransformerConfig, TransformerTrainer

mesh = make_mesh()
cfg = TransformerConfig(vocab=32768, embed=1024, n_layers=8,
                        n_heads=16, head_dim=64, ffn=4096)
tr = TransformerTrainer(mesh, cfg, learning_rate=1e-3)
params = tr.init_params()
rng = np.random.default_rng(0)
toks = rng.integers(0, cfg.vocab, size=(4, 2049)).astype(np.int32)
x, y = tr.place_batch(toks)
state = {"params": params}

def step():
    state["params"], loss = tr._train_step(state["params"], x, y)
    return loss

for _ in range(3):
    out = step()
jax.block_until_ready(out)
print("warm done", flush=True)
t0 = time.time()
for i in range(10):
    out = step()
jax.block_until_ready(out)
dt = (time.time() - t0) / 10
print(f"chained loop: {dt*1000:.2f} ms/step", flush=True)

# same but block every step
t0 = time.time()
for i in range(5):
    out = step()
    jax.block_until_ready(out)
dt = (time.time() - t0) / 5
print(f"blocked loop: {dt*1000:.2f} ms/step", flush=True)

# does block_until_ready lie? readback the value
t0 = time.time()
for i in range(5):
    out = step()
    v = float(out)
dt = (time.time() - t0) / 5
print(f"float-readback loop: {dt*1000:.2f} ms/step, last loss {v:.4f}", flush=True)

t0 = time.time()
for i in range(5):
    out = step()
    jax.block_until_ready(state["params"]["embed"])
dt = (time.time() - t0) / 5
print(f"block-on-params loop: {dt*1000:.2f} ms/step", flush=True)
