"""Device-side loop timing: N chained attention ops inside one jit.

Immune to the tunnel's dispatch/readback noise — the difference between a
20-iteration and a 4-iteration program is 16 iterations of pure device
time."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp
from functools import partial

B, T, H, D = 4, 2048, 16, 64
q = jax.random.normal(jax.random.key(0), (B, H, T, D), jnp.bfloat16)
k = jax.random.normal(jax.random.key(1), (B, H, T, D), jnp.bfloat16)
v = jax.random.normal(jax.random.key(2), (B, H, T, D), jnp.bfloat16)

from jax.experimental.pallas.ops.tpu import flash_attention as fa

fl = 2 * 2 * B * H * T * T * D  # fwd attention matmul flops (no causal /2)


def timed(make_step, name, flops, n_hi=16, n_lo=4):
    """make_step(x) -> x-like; chained under scan."""
    def prog(n):
        @jax.jit
        def run(x):
            def body(c, _):
                return make_step(c), None
            out, _ = jax.lax.scan(body, x, None, length=n)
            return jnp.sum(out.astype(jnp.float32))
        return run

    hi, lo = prog(n_hi), prog(n_lo)
    for f in (hi, lo):  # compile + warm
        float(f(q))
    ts = []
    for _ in range(3):
        t0 = time.time(); float(lo(q)); t_lo = time.time() - t0
        t0 = time.time(); float(hi(q)); t_hi = time.time() - t0
        ts.append((t_hi - t_lo) / (n_hi - n_lo))
    s = min(ts)
    print(f"{name:24s} {s*1e3:7.2f} ms ({flops/s/1e12:5.1f} TF/s)",
          flush=True)
    return s


# jnp reference (what the model's unchunked path does)
def jnp_attn(x):
    mask = jnp.tril(jnp.ones((T, T), bool))
    s = jnp.einsum("bhqd,bhkd->bhqk", x, k,
                   preferred_element_type=jnp.float32) * (D ** -0.5)
    s = jnp.where(mask[None, None], s, -jnp.inf)
    w = jax.nn.softmax(s, axis=-1).astype(jnp.bfloat16)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v,
                      preferred_element_type=jnp.float32).astype(jnp.bfloat16)


timed(jnp_attn, "jnp fwd", fl)


def g_jnp(x):
    return jax.grad(lambda q: jnp.sum(
        jnp_attn_q(q).astype(jnp.float32)))(x).astype(jnp.bfloat16)


def jnp_attn_q(qx):
    mask = jnp.tril(jnp.ones((T, T), bool))
    s = jnp.einsum("bhqd,bhkd->bhqk", qx, k,
                   preferred_element_type=jnp.float32) * (D ** -0.5)
    s = jnp.where(mask[None, None], s, -jnp.inf)
    w = jax.nn.softmax(s, axis=-1).astype(jnp.bfloat16)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v,
                      preferred_element_type=jnp.float32).astype(jnp.bfloat16)


timed(g_jnp, "jnp fwd+bwd(dq)", 3 * fl)

bs = fa.BlockSizes(
    block_q=512, block_k_major=512, block_k=512, block_b=1,
    block_q_major_dkv=512, block_k_major_dkv=512,
    block_k_dkv=512, block_q_dkv=512,
    block_k_major_dq=512, block_k_dq=512, block_q_dq=512,
)


def pl_attn(x):
    return fa.flash_attention(x, k, v, causal=True, sm_scale=D ** -0.5,
                              block_sizes=bs)


timed(pl_attn, "pallas fwd c512", fl)


def g_pl(x):
    return jax.grad(lambda q: jnp.sum(
        pl_attn(q).astype(jnp.float32)))(x).astype(jnp.bfloat16)


timed(g_pl, "pallas fwd+bwd(dq) c512", 3 * fl)
