"""Isolate: view-vs-copy device_put, and transfers interleaved with
dispatched compute (the wave pipeline pattern)."""
import sys, os
sys.path.insert(0, "/root/repo")
import time
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
from mapreduce_tpu.parallel import make_mesh

MB = 1 << 20
mesh = make_mesh()
sh = NamedSharding(mesh, P("data"))
W = 8

def fresh():
    return np.random.default_rng(None).integers(
        0, 255, size=(96, 4 * MB), dtype=np.uint8)  # 384MB

def timed(label, fn):
    t0 = time.time()
    fn()
    dt = time.time() - t0
    print(f"{label:46s} {dt:6.2f}s {384 / dt:7.1f} MB/s", flush=True)

# A: 8 sharded puts of contiguous VIEWS, no compute
def views_only():
    big = fresh()
    outs = [jax.device_put(big[w * 12:(w + 1) * 12], sh) for w in range(W)]
    jax.block_until_ready(outs)
timed("A 8 sharded puts of views", views_only)
timed("A2 8 sharded puts of views", views_only)

# B: same but np.ascontiguousarray copies
def copies():
    big = fresh()
    outs = [jax.device_put(big[w * 12:(w + 1) * 12].copy(), sh)
            for w in range(W)]
    jax.block_until_ready(outs)
timed("B 8 sharded puts of copies", copies)

# C: one put of the whole array
def one_put():
    big = fresh()
    jax.block_until_ready(jax.device_put(big, sh))
timed("C single sharded put 384MB", one_put)
timed("C2 single sharded put 384MB", one_put)

# D: views interleaved with a dispatched reduction per wave
red = jax.jit(lambda x: jnp.sum(x.astype(jnp.int32)))
def interleaved():
    big = fresh()
    outs = []
    for w in range(W):
        d = jax.device_put(big[w * 12:(w + 1) * 12], sh)
        outs.append(red(d))
    jax.block_until_ready(outs)
_ = red(jax.device_put(fresh()[:12], sh))  # warm compile
timed("D views + dispatched compute per wave", interleaved)
timed("D2 views + dispatched compute per wave", interleaved)
