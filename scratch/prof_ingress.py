"""True host->device ingress rate, pre- vs post-execution (round 4).

Round 3 believed the tunnel served a "cold client's" pre-execution
transfers 25-50x faster than post-execution ones.  That was an artifact:
jax.block_until_ready returns while transfers are still in flight on
this platform, so staging "completed" in 0.7s while the bytes kept
trickling.  Forcing residency with a checksum program (a scalar that
cannot exist until every staged buffer landed) shows the truth:

    stage+forced residency (copy 1, pre-exec):  23.1s
    stage+forced residency (copy 2, post-exec): 22.5s
    checksum alone (resident):                   0.11s

i.e. ~13MB/s in BOTH execution states — there is no fast path and no
demotion; there is one slow tunnel.  Consequence: bench.py reports
ingress separately (with a residency barrier in stage_inputs) and times
the pipeline from verified-resident HBM, matching the reference's clock
(its corpus pre-exists in cluster storage).
"""
import sys, time
sys.path.insert(0, "/root/repo")
from mapreduce_tpu.utils.compile_cache import enable_persistent_cache
enable_persistent_cache()
import jax, jax.numpy as jnp
import numpy as np
from bench import make_corpus
from mapreduce_tpu.engine import DeviceWordCount
from mapreduce_tpu.engine.wordcount import bench_engine_config
from mapreduce_tpu.parallel import make_mesh

corpus = make_corpus(49_158_635, 1_965_734)
wc = DeviceWordCount(make_mesh(), chunk_len=1 << 22,
                     config=bench_engine_config())

chk = jax.jit(lambda *cs: sum(jnp.sum(c[:, ::4096].astype(jnp.int32))
                              for c in cs))

t0 = time.time()
h1 = wc.stage(corpus)   # includes the residency barrier now
print(f"stage (verified, copy 1): {time.time()-t0:.2f}s", flush=True)

t0 = time.time()
counts = wc.count_staged(h1)
print(f"count_staged: {time.time()-t0:.2f}s, {len(counts)} uniques",
      flush=True)

t0 = time.time()
h2 = wc.stage(corpus)
print(f"stage (verified, copy 2, post-exec): {time.time()-t0:.2f}s",
      flush=True)
t0 = time.time()
int(np.asarray(chk(*[ci for ci, _ in h2[2][0]])))
print(f"checksum alone (resident): {time.time()-t0:.2f}s", flush=True)
