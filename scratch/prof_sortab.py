"""A/B: variadic 2-key sort vs two-pass stable argsort + gathers, at the
bench's record-buffer shape, runtime AND compile (TPU)."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp

N = 11_075_584
u32, i32 = np.uint32, np.int32

rng = np.random.default_rng(0)
k1 = rng.integers(0, 1 << 32, size=N, dtype=np.uint64).astype(u32)
k2 = rng.integers(0, 1 << 32, size=N, dtype=np.uint64).astype(u32)
p1 = rng.integers(0, 1 << 30, size=N).astype(i32)
p2 = rng.integers(0, 1 << 30, size=N).astype(i32)


def variadic(k1, k2, p1, p2):
    out = jax.lax.sort((k1, k2, p1, p2), num_keys=2)
    return tuple(out)


def twopass(k1, k2, p1, p2):
    perm2 = jnp.argsort(k2, stable=True)
    perm = perm2[jnp.argsort(k1[perm2], stable=True)]
    return k1[perm], k2[perm], p1[perm], p2[perm]


def run(fn, name):
    t0 = time.time()
    j = jax.jit(fn)
    o = j(k1, k2, p1, p2)
    jax.block_until_ready(o)
    comp = time.time() - t0
    best = 1e9
    for _ in range(5):
        t0 = time.time()
        o = j(k1, k2, p1, p2)
        np.asarray(o[0]).ravel()[:1]
        best = min(best, time.time() - t0)
    print(f"{name:12s} compile+1st {comp:6.1f}s  run {best*1e3:7.1f} ms",
          flush=True)
    return o


a = run(variadic, "variadic")
b = run(twopass, "twopass")
for x, y in zip(a, b):
    assert np.array_equal(np.asarray(x), np.asarray(y)), "MISMATCH"
print("results identical", flush=True)
