"""Attribute the first-run warmup (VERDICT r4 weak 2: BENCH_r04 showed
compile_s 43.2 = AOT 12.0 + ~31s first end-to-end run, cache present).

Where does the first count_bytes go that the second doesn't?  Stage-level
diff of run1 vs run2 timings on a mid-size corpus, plus a separate
second-process rerun to see what a WARM machine (cache + server process
restart) pays.
"""
import sys, time, os
sys.path.insert(0, "/root/repo")
import numpy as np

from mapreduce_tpu.utils.compile_cache import enable_persistent_cache

enable_persistent_cache()
import jax

from bench import make_corpus, N_WORDS, N_LINES
from mapreduce_tpu.engine import DeviceWordCount
from mapreduce_tpu.engine.wordcount import bench_engine_config
from mapreduce_tpu.parallel import make_mesh

SCALE = float(os.environ.get("SCALE", "0.1"))

t0 = time.time()
corpus = make_corpus(int(N_WORDS * SCALE), int(N_LINES * SCALE))
print(f"corpus {len(corpus)/1e6:.0f}MB in {time.time()-t0:.1f}s",
      flush=True)

wc = DeviceWordCount(make_mesh(), chunk_len=1 << 22,
                     config=bench_engine_config())

t0 = time.time()
aot = wc.warm()
print(f"warm() AOT: {aot:.1f}s (wall {time.time()-t0:.1f}s)", flush=True)

for r in range(3):
    tm = {}
    t0 = time.time()
    counts = wc.count_bytes(corpus, timings=tm)
    wall = time.time() - t0
    print(f"run{r}: wall {wall:6.2f}s  stages: "
          + " ".join(f"{k}={v}" for k, v in sorted(tm.items())
                     if isinstance(v, (int, float))), flush=True)
print(f"uniques={len(counts)}", flush=True)
